"""Expression IR: typed tree lowering to whole-column jnp programs.

The reference implements ~400 expressions with dual interpreted/codegen
paths (`sql/catalyst/.../expressions/Expression.scala:86` — `eval:129` and
`doGenCode:202`). Here there is a single path: ``eval`` builds a traced
jnp computation over whole columns; "codegen" is ``jax.jit`` of the
composed program — XLA fusion replaces Janino whole-stage codegen
(`CodeGenerator.scala:1435`, `WholeStageCodegenExec.scala:626`).

Null semantics follow the reference: NULL-propagating arithmetic,
Kleene three-valued AND/OR, null-safe IsNull/IsNotNull. NULLs ride a
boolean validity array (None == all valid), mirroring validity bitmaps of
`ColumnVector.java` rather than UnsafeRow null bits.

String expressions are dictionary-aware: comparisons/LIKE against
literals are evaluated once on the host-side dictionary and become O(1)
code lookups on device (SURVEY.md section 7 "Strings/varlen on TPU").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from . import types as T
from .columnar import Batch, Column


@dataclass
class Vec:
    """An evaluated column-expression: data + validity + type + dictionary.

    `bits`: optional static bound — values are known to lie in
    [0, 2^bits). Sources with known ranges (Range ids) set it so int64
    arithmetic can take single-pass f64 fast paths (TPU emulates both
    int64 and f64 in software; one emulated pass instead of three is
    measurable at bench scales)."""

    data: Any
    dtype: T.DataType
    validity: Any = None  # None = all valid
    dictionary: Optional[pa.Array] = None
    bits: Optional[int] = None
    # ARRAY columns: flattened-element layout (columnar.Column contract)
    offsets: Any = None
    elem_validity: Any = None

    def valid_mask(self):
        if self.validity is None:
            return None
        return self.validity


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


class AnalysisError(Exception):
    pass


class Expression:
    """Base expression node."""

    children: Tuple["Expression", ...] = ()

    def dtype(self, schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def nullable(self, schema: T.Schema) -> bool:
        return any(c.nullable(schema) for c in self.children) if self.children else True

    def eval(self, batch: Batch) -> Vec:
        raise NotImplementedError

    def name(self) -> str:
        return repr(self)

    # -- tree utilities (reference: TreeNode.scala transform combinators) ---

    def map_children(self, f: Callable[["Expression"], "Expression"]) -> "Expression":
        if not self.children:
            return self
        import copy
        new = copy.copy(self)
        new.children = tuple(f(c) for c in self.children)
        return new

    def transform_up(self, f) -> "Expression":
        node = self.map_children(lambda c: c.transform_up(f))
        return f(node)

    def references(self) -> set:
        out = set()
        for c in self.children:
            out |= c.references()
        return out

    def foldable(self) -> bool:
        return bool(self.children) and all(c.foldable() for c in self.children)

    # sugar so users can compose: (col("a") + 1 > col("b")) & ...
    def __add__(self, o): return Add(self, _wrap(o))
    def __radd__(self, o): return Add(_wrap(o), self)
    def __sub__(self, o): return Sub(self, _wrap(o))
    def __rsub__(self, o): return Sub(_wrap(o), self)
    def __mul__(self, o): return Mul(self, _wrap(o))
    def __rmul__(self, o): return Mul(_wrap(o), self)
    def __truediv__(self, o): return Div(self, _wrap(o))
    def __rtruediv__(self, o): return Div(_wrap(o), self)
    def __mod__(self, o): return Mod(self, _wrap(o))
    def __neg__(self): return Neg(self)
    def __eq__(self, o): return EQ(self, _wrap(o))  # type: ignore[override]
    def __ne__(self, o): return NE(self, _wrap(o))  # type: ignore[override]
    def __lt__(self, o): return LT(self, _wrap(o))
    def __le__(self, o): return LE(self, _wrap(o))
    def __gt__(self, o): return GT(self, _wrap(o))
    def __ge__(self, o): return GE(self, _wrap(o))
    def __and__(self, o): return And(self, _wrap(o))
    def __rand__(self, o): return And(_wrap(o), self)
    def __or__(self, o): return Or(self, _wrap(o))
    def __ror__(self, o): return Or(_wrap(o), self)
    def __invert__(self): return Not(self)
    def __hash__(self):
        return hash((type(self).__name__, self.children))

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dt: T.DataType) -> "Cast":
        return Cast(self, dt)

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "Not":
        return Not(IsNull(self))

    def isin(self, *values) -> "In":
        return In(self, tuple(values))

    def between(self, lo, hi) -> "Expression":
        return And(GE(self, _wrap(lo)), LE(self, _wrap(hi)))

    def like(self, pattern: str) -> "Like":
        return Like(self, pattern)

    def startswith(self, prefix: str) -> "Like":
        return Like(self, prefix.replace("%", r"\%").replace("_", r"\_") + "%")

    def substr(self, start: int, length: int) -> "Substring":
        return Substring(self, start, length)

    def asc(self) -> "SortOrder":
        return SortOrder(self, ascending=True)

    def desc(self) -> "SortOrder":
        return SortOrder(self, ascending=False)


def _wrap(v) -> Expression:
    if isinstance(v, Expression):
        return v
    return Literal(v)


def structurally_equal(a: Expression, b: Expression) -> bool:
    """Semantic (structural) equality — `__eq__` is overloaded for DSL use."""
    if type(a) is not type(b):
        return False
    sa = {k: v for k, v in a.__dict__.items() if k != "children"}
    sb = {k: v for k, v in b.__dict__.items() if k != "children"}
    if sa.keys() != sb.keys():
        return False
    for k in sa:
        va, vb = sa[k], sb[k]
        if isinstance(va, Expression) or isinstance(vb, Expression):
            if not (isinstance(va, Expression) and isinstance(vb, Expression)
                    and structurally_equal(va, vb)):
                return False
        elif va is not vb and va != vb:
            return False
    if len(a.children) != len(b.children):
        return False
    return all(structurally_equal(x, y) for x, y in zip(a.children, b.children))


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class ColumnRef(Expression):
    """Unresolved-by-name column reference (reference: UnresolvedAttribute)."""

    def __init__(self, name: str):
        self._name = name
        self.children = ()

    def dtype(self, schema: T.Schema) -> T.DataType:
        return _resolve_field(schema, self._name).dtype

    def nullable(self, schema: T.Schema) -> bool:
        return _resolve_field(schema, self._name).nullable

    def eval(self, batch: Batch) -> Vec:
        col = _resolve_column(batch, self._name)
        return Vec(col.data, col.dtype, col.validity, col.dictionary,
                   bits=getattr(col, "bits", None),
                   offsets=col.offsets, elem_validity=col.elem_validity)

    def references(self) -> set:
        return {self._name}

    def foldable(self) -> bool:
        return False

    def name(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return self._name


# session-level resolution mode, set from spark_tpu.sql.caseSensitive by
# the executor before analysis/tracing. A ContextVar rather than a bare
# module global: the SQL service runs concurrent queries from sessions
# with different caseSensitive overlays on separate threads, and each
# thread's activation must not stomp the others (the reference's
# thread-inheritable SQLConf activation, contextvars edition).
from contextvars import ContextVar

_CASE_SENSITIVE: ContextVar[bool] = ContextVar(
    "spark_tpu_case_sensitive", default=False)


def case_sensitive() -> bool:
    return _CASE_SENSITIVE.get()


def set_case_sensitive(value: bool) -> None:
    _CASE_SENSITIVE.set(bool(value))


def _resolve_field(schema: T.Schema, name: str) -> T.Field:
    matches = [f for f in schema.fields if f.name == name]
    if not matches and not case_sensitive():
        matches = [f for f in schema.fields if f.name.lower() == name.lower()]
    if not matches:
        raise AnalysisError(
            f"column {name!r} not found among {schema.names}")
    if len(matches) > 1:
        raise AnalysisError(f"ambiguous column {name!r}")
    return matches[0]


def _resolve_column(batch: Batch, name: str) -> Column:
    if name in batch.columns:
        return batch.columns[name]
    if not case_sensitive():
        for n, c in batch.columns.items():
            if n.lower() == name.lower():
                return c
    raise AnalysisError(f"column {name!r} not found among {batch.names}")


class Literal(Expression):
    def __init__(self, value, dtype: Optional[T.DataType] = None):
        self.value = value
        self._dtype = dtype or _infer_literal_type(value)
        self.children = ()

    def dtype(self, schema=None) -> T.DataType:
        return self._dtype

    def nullable(self, schema=None) -> bool:
        return self.value is None

    def foldable(self) -> bool:
        return True

    def eval(self, batch: Batch) -> Vec:
        return self.eval_scalar()

    def eval_scalar(self) -> Vec:
        if self.value is None:
            # NULL strings carry a placeholder dictionary so unions/ops
            # see a well-formed dictionary column (validity is false
            # everywhere, so the placeholder value never materializes;
            # a 0-length dictionary would break code remapping)
            dic = pa.array([""]) \
                if isinstance(self._dtype, T.StringType) else None
            return Vec(jnp.zeros((), dtype=self._dtype.np_dtype), self._dtype,
                       validity=jnp.zeros((), dtype=jnp.bool_),
                       dictionary=dic)
        v = self.value
        if isinstance(self._dtype, T.DecimalType):
            import decimal
            if not isinstance(v, decimal.Decimal):
                # via str() so 0.05 means 5e-2, and with the same HALF_UP
                # as the Decimal path (round() would banker's-round ties)
                v = decimal.Decimal(str(v))
            v = int((v * (10 ** self._dtype.scale)).to_integral_value(
                rounding=decimal.ROUND_HALF_UP))
        if isinstance(self._dtype, T.DateType):
            import datetime
            if isinstance(v, datetime.date):
                v = (v - datetime.date(1970, 1, 1)).days
        if isinstance(self._dtype, T.StringType):
            # scalar strings stay host-side; comparisons special-case them
            return Vec(None, self._dtype, None, None)
        return Vec(jnp.asarray(v, dtype=self._dtype.np_dtype), self._dtype)

    def __repr__(self) -> str:
        return repr(self.value)


def _infer_literal_type(v) -> T.DataType:
    import datetime
    import decimal
    if v is None:
        return T.NULL
    if isinstance(v, bool):
        return T.BOOLEAN
    if isinstance(v, int):
        return T.LONG if not (-2**31 <= v < 2**31) else T.INT
    if isinstance(v, float):
        return T.DOUBLE
    if isinstance(v, str):
        return T.STRING
    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = max(0, -exp)
        return T.DecimalType(max(len(digits), scale + 1), scale)
    if isinstance(v, datetime.date) and not isinstance(v, datetime.datetime):
        return T.DATE
    if isinstance(v, datetime.datetime):
        return T.TIMESTAMP
    raise TypeError(f"cannot infer literal type for {v!r}")


def date_literal(s: str) -> Literal:
    """'1998-09-02' -> days-since-epoch DATE literal."""
    days = (np.datetime64(s, "D") - np.datetime64("1970-01-01", "D")).astype(int)
    lit = Literal(int(days), T.DATE)
    return lit


class Alias(Expression):
    def __init__(self, child: Expression, alias_name: str):
        self.children = (child,)
        self._alias = alias_name

    @property
    def child(self):
        return self.children[0]

    def dtype(self, schema):
        return self.child.dtype(schema)

    def nullable(self, schema):
        return self.child.nullable(schema)

    def eval(self, batch):
        return self.child.eval(batch)

    def name(self) -> str:
        return self._alias

    def __repr__(self) -> str:
        return f"{self.children[0]!r} AS {self._alias}"


class SortOrder(Expression):
    """Sort key + direction + null ordering (reference: SortOrder.scala)."""

    def __init__(self, child: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.children = (child,)
        self.ascending = ascending
        # Spark default: NULLS FIRST for ASC, NULLS LAST for DESC
        self.nulls_first = ascending if nulls_first is None else nulls_first

    @property
    def child(self):
        return self.children[0]

    def dtype(self, schema):
        return self.child.dtype(schema)

    def eval(self, batch):
        return self.child.eval(batch)

    def __repr__(self):
        return f"{self.children[0]!r} {'ASC' if self.ascending else 'DESC'}"


# ---------------------------------------------------------------------------
# Casts and numeric helpers
# ---------------------------------------------------------------------------

class Cast(Expression):
    def __init__(self, child: Expression, to: T.DataType):
        self.children = (child,)
        self.to = to

    def dtype(self, schema):
        return self.to

    def eval(self, batch: Batch) -> Vec:
        v = self.children[0].eval(batch)
        return cast_vec(v, self.to)

    def __repr__(self):
        return f"cast({self.children[0]!r} as {self.to!r})"


def cast_vec(v: Vec, to: T.DataType) -> Vec:
    if v.dtype == to:
        return v
    src = v.dtype
    data = v.data
    if isinstance(src, T.DecimalType) and isinstance(to, T.DecimalType):
        ds = to.scale - src.scale
        if ds >= 0:
            data = data * (10 ** ds)
        else:
            data = _div_round_half_up(data, 10 ** (-ds))
        return Vec(data, to, v.validity)
    if isinstance(src, T.DecimalType):
        if isinstance(to, (T.DoubleType, T.FloatType)):
            return Vec((data / (10.0 ** src.scale)).astype(to.np_dtype), to, v.validity)
        if isinstance(to, T.IntegralType):
            return Vec(_div_round_half_up(data, 10 ** src.scale).astype(to.np_dtype),
                       to, v.validity)
    if isinstance(to, T.DecimalType):
        if isinstance(src, T.IntegralType) or isinstance(src, T.BooleanType):
            return Vec(data.astype(np.int64) * (10 ** to.scale), to, v.validity)
        if isinstance(src, (T.DoubleType, T.FloatType)):
            scaled = jnp.round(data.astype(np.float64) * (10.0 ** to.scale))
            return Vec(scaled.astype(np.int64), to, v.validity)
    if isinstance(src, T.StringType) or isinstance(to, T.StringType):
        raise AnalysisError(f"cast {src!r} -> {to!r} not supported on device")
    return Vec(data.astype(to.np_dtype), to, v.validity)


def _div_round_half_up(data, divisor: int):
    # HALF_UP rounding on integers, matching the reference Decimal.scala
    half = divisor // 2
    adj = jnp.where(data >= 0, data + half, data - half)
    return adj // divisor


# ---------------------------------------------------------------------------
# Arithmetic
# ---------------------------------------------------------------------------

class BinaryArithmetic(Expression):
    op: str = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def dtype(self, schema):
        lt = self.children[0].dtype(schema)
        rt = self.children[1].dtype(schema)
        return self._result_type(lt, rt)

    def _result_type(self, lt, rt):
        return T.common_type(lt, rt)

    def eval(self, batch: Batch) -> Vec:
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        validity = _and_valid(lv.validity, rv.validity)
        out_dtype = self._result_type(lv.dtype, rv.dtype)
        data = self._compute(lv, rv, out_dtype)
        return Vec(data, out_dtype, validity)

    def _compute(self, lv: Vec, rv: Vec, out: T.DataType):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.children[0]!r} {self.op} {self.children[1]!r})"


def _align(v: Vec, out: T.DataType):
    return cast_vec(v, out).data


class Add(BinaryArithmetic):
    op = "+"

    def _compute(self, lv, rv, out):
        return _align(lv, out) + _align(rv, out)


class Sub(BinaryArithmetic):
    op = "-"

    def _compute(self, lv, rv, out):
        return _align(lv, out) - _align(rv, out)


class Mul(BinaryArithmetic):
    op = "*"

    def _result_type(self, lt, rt):
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            ls = lt.scale if isinstance(lt, T.DecimalType) else 0
            rs = rt.scale if isinstance(rt, T.DecimalType) else 0
            lp = lt.precision if isinstance(lt, T.DecimalType) else 20
            rp = rt.precision if isinstance(rt, T.DecimalType) else 20
            if isinstance(lt, T.NumericType) and isinstance(rt, T.NumericType) \
                    and not isinstance(lt, (T.FloatType, T.DoubleType)) \
                    and not isinstance(rt, (T.FloatType, T.DoubleType)):
                return T.DecimalType(min(38, lp + rp), ls + rs)
            return T.DOUBLE
        return T.common_type(lt, rt)

    def _compute(self, lv, rv, out):
        if isinstance(out, T.DecimalType):
            l = lv.data if isinstance(lv.dtype, T.DecimalType) else \
                cast_vec(lv, T.DecimalType(20, 0)).data
            r = rv.data if isinstance(rv.dtype, T.DecimalType) else \
                cast_vec(rv, T.DecimalType(20, 0)).data
            return l * r
        return _align(lv, out) * _align(rv, out)


class Div(BinaryArithmetic):
    """`/`: true division. Integer/integer -> double (Spark SQL), and
    decimal division returns a DECIMAL quotient per the reference's
    `DecimalPrecision` rule (scale = max(6, s1+p2+1)) — capped at scale 8
    here because the device representation is scaled int64, not int128
    (documented deviation; values are HALF_UP-rounded at that scale).
    Division by zero yields NULL (non-ANSI reference behavior)."""

    op = "/"

    def nullable(self, schema):
        return True

    def _result_type(self, lt, rt):
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            if isinstance(lt, (T.FloatType, T.DoubleType)) or \
                    isinstance(rt, (T.FloatType, T.DoubleType)):
                return T.DOUBLE
            s1 = lt.scale if isinstance(lt, T.DecimalType) else 0
            p1 = lt.precision if isinstance(lt, T.DecimalType) else 20
            s2 = rt.scale if isinstance(rt, T.DecimalType) else 0
            p2 = rt.precision if isinstance(rt, T.DecimalType) else 20
            scale = min(max(6, s1 + p2 + 1), 8)
            prec = min(38, p1 - s1 + s2 + scale)
            return T.DecimalType(prec, scale)
        return T.DOUBLE

    def eval(self, batch: Batch) -> Vec:
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        out = self._result_type(lv.dtype, rv.dtype)
        validity = _and_valid(lv.validity, rv.validity)
        if isinstance(out, T.DecimalType):
            s1 = lv.dtype.scale if isinstance(lv.dtype, T.DecimalType) else 0
            s2 = rv.dtype.scale if isinstance(rv.dtype, T.DecimalType) else 0
            l = lv.data if isinstance(lv.dtype, T.DecimalType) else \
                cast_vec(lv, T.DecimalType(20, 0)).data
            r = rv.data if isinstance(rv.dtype, T.DecimalType) else \
                cast_vec(rv, T.DecimalType(20, 0)).data
            zero = r == 0
            safe_r = jnp.where(zero, jnp.ones((), r.dtype), r)
            # unscaled_out = l / r * 10^(out.scale + s2 - s1), HALF_UP,
            # via f64. Exactness needs the scaled numerator AND the
            # divisor inside the 2^53 mantissa; rows past the bound go
            # NULL instead of silently rounding (round-4 VERDICT weak
            # #4 — the reference raises/NULLs per ANSI mode).
            shift = out.scale + s2 - s1
            if shift >= 0:
                l_bound = (1 << 53) // (10 ** shift)
            else:
                l_bound = (1 << 53) * (10 ** (-shift))
            exact = (jnp.abs(l) <= jnp.int64(min(l_bound, (1 << 62)))) \
                & (jnp.abs(r) <= jnp.int64(1 << 53))
            q = (l.astype(jnp.float64) * (10.0 ** shift)
                 / safe_r.astype(jnp.float64))
            data = (jnp.sign(q) * jnp.floor(jnp.abs(q) + 0.5)).astype(jnp.int64)
            extra = ~zero & exact
        else:
            l = cast_vec(lv, T.DOUBLE).data
            r = cast_vec(rv, T.DOUBLE).data
            zero = r == 0.0
            data = l / jnp.where(zero, jnp.ones((), r.dtype), r)
            extra = ~zero
        validity = _and_valid(validity, extra)
        if validity is not None and np.ndim(validity) == 0:
            validity = jnp.broadcast_to(validity, np.shape(data))
        return Vec(data, out, validity)

    def _compute(self, lv, rv, out):
        raise AssertionError("Div.eval is overridden")


class Mod(BinaryArithmetic):
    """`%` with the reference's truncated-division semantics
    (`arithmetic.scala` Remainder): the result carries the sign of the
    DIVIDEND (-7 % 3 == -1). `Pmod` is the positive variant (result in
    [0, |m|)). Division by zero yields NULL (non-ANSI reference behavior)."""

    op = "%"
    _positive = False  # Pmod overrides

    def nullable(self, schema):
        return True  # divisor may be zero

    def _compute_valid(self, lv, rv, out):
        div_expr = self.children[1]
        while isinstance(div_expr, (Alias, Cast)):
            div_expr = div_expr.children[0]
        if (isinstance(div_expr, Literal)
                and isinstance(div_expr.value, int)
                and 0 < div_expr.value < (1 << 26)
                and isinstance(lv.dtype, T.IntegralType)
                and isinstance(out, T.IntegralType)):
            # TPU has no integer divide; `%` lowers to a slow emulation
            # (~0.9ns/elem measured). For a constant positive divisor,
            # strength-reduce via exact f64 reciprocal-multiply.
            m = int(div_expr.value)
            x = lv.data

            def f64_mod(v):
                # exact for 0 <= v < 2^52: reciprocal multiply + correction
                q = jnp.floor(v.astype(jnp.float64) * (1.0 / m))
                r = v - q.astype(jnp.int64) * m
                return jnp.where(r < 0, r + m,
                                 jnp.where(r >= m, r - m, r))

            if np.dtype(x.dtype).itemsize <= 4 or \
                    (lv.bits is not None and lv.bits <= 52):
                # int64 with a static value bound < 2^52: one exact
                # f64 pass instead of the three-mod halves ladder
                r = f64_mod(x.astype(jnp.int64))
            else:
                # int64: u32-half mods (f64-exact) + recombination < m^2 < 2^52
                xu_lo = (x & jnp.int64(0xFFFFFFFF))
                xu_hi = ((x >> 32) & jnp.int64(0xFFFFFFFF))
                pow32_m = (1 << 32) % m
                pow64_m = (1 << 64) % m
                combined = f64_mod(xu_hi) * pow32_m + f64_mod(xu_lo)
                r = f64_mod(combined)
                # x (signed) = x_u - 2^64*[x<0]; adjust modulo m
                r = jnp.where(x < 0, r - pow64_m, r)
                r = jnp.where(r < 0, r + m, r)
                r = jnp.where(r >= m, r - m, r)
            if not self._positive:
                # fast path computed pmod; shift to truncated semantics
                r = jnp.where((x < 0) & (r != 0), r - m, r)
            return r.astype(out.np_dtype), None
        l = _align(lv, out)
        r = _align(rv, out)
        zero = r == jnp.zeros((), r.dtype)
        safe_r = jnp.where(zero, jnp.ones((), r.dtype), r)
        fr = l % safe_r  # floored: sign of divisor
        if self._positive:
            res = jnp.where(fr < 0, fr + jnp.abs(safe_r), fr)
        else:
            # truncated: sign of dividend
            sign_mismatch = (l < 0) != (safe_r < jnp.zeros((), safe_r.dtype))
            res = jnp.where((fr != 0) & sign_mismatch, fr - safe_r, fr)
        return res, ~zero

    def _compute(self, lv, rv, out):
        data, _ = self._compute_valid(lv, rv, out)
        return data

    def eval(self, batch: Batch) -> Vec:
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        out_dtype = self._result_type(lv.dtype, rv.dtype)
        data, extra_valid = self._compute_valid(lv, rv, out_dtype)
        validity = _and_valid(_and_valid(lv.validity, rv.validity), extra_valid)
        if validity is not None and np.ndim(validity) == 0:
            validity = jnp.broadcast_to(validity, np.shape(data))
        return Vec(data, out_dtype, validity)


class Pmod(Mod):
    """pmod(a, m): positive modulo, result in [0, |m|) (the reference's
    `Pmod`, arithmetic.scala). The dense-domain group-by path keys on this."""

    op = "pmod"
    _positive = True

    def __repr__(self):
        return f"pmod({self.children[0]!r}, {self.children[1]!r})"


def static_unsigned_bits(e: "Expression") -> Optional[int]:
    """Static bound w with values of e in [0, 2^w), or None. Lets SUM
    accumulators carry only the limbs the value range needs in the MXU
    group-by kernel (pallas_groupby._limb_layout)."""
    while isinstance(e, Alias):
        e = e.children[0]
    if isinstance(e, Pmod):
        d = e.children[1]
        while isinstance(d, (Alias, Cast)):
            d = d.children[0]
        if isinstance(d, Literal) and isinstance(d.value, int) \
                and d.value > 0:
            return max(1, (d.value - 1).bit_length())
    if isinstance(e, Literal) and isinstance(e.value, int) \
            and not isinstance(e.value, bool) and e.value >= 0:
        return max(1, int(e.value).bit_length())
    return None


class Neg(Expression):
    def __init__(self, child):
        self.children = (child,)

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def eval(self, batch):
        v = self.children[0].eval(batch)
        return Vec(-v.data, v.dtype, v.validity)

    def __repr__(self):
        return f"(-{self.children[0]!r})"


def _civil_from_days(days):
    """days-since-epoch -> (year, month, day), branch-free (Howard
    Hinnant's civil-from-days algorithm, vectorized)."""
    z = days + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


class _ExtractDatePart(Expression):
    """year/month/day(date) (reference: datetimeExpressions.scala)."""

    _part = "year"

    def __init__(self, child):
        self.children = (child,)

    def dtype(self, schema):
        return T.INT

    def eval(self, batch):
        v = self.children[0].eval(batch)
        x = v.data.astype(jnp.int64)
        if isinstance(v.dtype, T.TimestampType):
            # microseconds -> days (// floors, so pre-epoch is correct)
            x = x // jnp.int64(86_400_000_000)
        y, m, d = _civil_from_days(x)
        part = {"year": y, "month": m, "day": d}[self._part]
        return Vec(part.astype(jnp.int32), T.INT, v.validity)

    def __repr__(self):
        return f"{self._part}({self.children[0]!r})"


class ExtractYear(_ExtractDatePart):
    _part = "year"


class ExtractMonth(_ExtractDatePart):
    _part = "month"


class ExtractDay(_ExtractDatePart):
    _part = "day"


class DateAdd(Expression):
    """date_add(date, n): shift by days (reference: DateAdd)."""

    def __init__(self, child, days: Expression):
        self.children = (child, days)

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        n = self.children[1].eval(batch)
        x = v.data
        if isinstance(v.dtype, T.TimestampType):
            # like the reference, the timestamp is cast to DATE first
            x = x.astype(jnp.int64) // jnp.int64(86_400_000_000)
        data = (x.astype(jnp.int32) + n.data.astype(jnp.int32))
        return Vec(data, T.DATE, _and_valid(v.validity, n.validity))

    def __repr__(self):
        return f"date_add({self.children[0]!r}, {self.children[1]!r})"


# ---------------------------------------------------------------------------
# Predicates (three-valued logic; reference: predicates.scala)
# ---------------------------------------------------------------------------

class BinaryComparison(Expression):
    op = "?"

    def __init__(self, left, right):
        self.children = (left, right)

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch: Batch) -> Vec:
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        # dictionary-encoded string vs host string literal
        if isinstance(lv.dtype, T.StringType) or isinstance(rv.dtype, T.StringType):
            return self._eval_string(lv, rv, batch)
        # decimal column vs float scalar: comparing through f64 is exact on
        # CPU but NOT on TPU (f64 is emulated at <53-bit precision there:
        # 5/100.0 evaluates below 0.05, silently dropping boundary rows —
        # the round-2 TPC-H Q6 on-hardware divergence). Rewrite to an
        # integer compare on the unscaled decimal against a host-computed
        # boundary that replicates host-f64 semantics bit-for-bit.
        for a, b, b_expr, flip in ((lv, rv, self.children[1], False),
                                   (rv, lv, self.children[0], True)):
            lit = _host_float_value(b_expr, b.dtype)
            if isinstance(a.dtype, T.DecimalType) \
                    and isinstance(b.dtype, (T.DoubleType, T.FloatType)) \
                    and lit is not None:
                op = _flip_op(self.op) if flip else self.op
                data = _decimal_vs_float_scalar(a.data, a.dtype.scale,
                                                lit, op)
                if data is not None:
                    return Vec(data, T.BOOLEAN,
                               _and_valid(lv.validity, rv.validity))
        out = T.common_type(lv.dtype, rv.dtype)
        l = _align(lv, out)
        r = _align(rv, out)
        return Vec(self._cmp(l, r), T.BOOLEAN, _and_valid(lv.validity, rv.validity))

    def _eval_string(self, lv: Vec, rv: Vec, batch: Batch) -> Vec:
        lit = None
        colv = None
        for a, b in ((lv, rv), (rv, lv)):
            if a.data is None and a.dictionary is None:
                lit, colv = a, b
        if lit is None:
            # column-vs-column string compare: only EQ/NE via shared dictionary
            if lv.dictionary is not None and rv.dictionary is not None \
                    and lv.dictionary.equals(rv.dictionary) \
                    and type(self) in (EQ, NE):
                return Vec(self._cmp(lv.data, rv.data), T.BOOLEAN,
                           _and_valid(lv.validity, rv.validity))
            raise AnalysisError(
                f"string comparison {self.op} requires a literal or shared "
                f"dictionary")
        # evaluate the comparison on the host dictionary once, then gather
        lit_expr = self.children[0] if lv is lit else self.children[1]
        value = lit_expr.value  # type: ignore[attr-defined]
        table = _dict_compare_table(colv.dictionary, value,
                                    self.op if colv is lv or type(self) in (EQ, NE)
                                    else _flip_op(self.op))
        if len(table) == 0:
            # all-null column: the dictionary is empty, so no code is
            # valid and the payload is masked everywhere
            data = jnp.zeros(colv.data.shape, dtype=bool)
        else:
            data = jnp.take(table,
                            jnp.clip(colv.data, 0, len(table) - 1))
        return Vec(data, T.BOOLEAN, colv.validity)

    def _cmp(self, l, r):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.children[0]!r} {self.op} {self.children[1]!r})"


def _flip_op(op: str) -> str:
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}[op]


def _host_float_value(e: "Expression", dtype: T.DataType) -> Optional[float]:
    """Host-side float value of a literal expression (the evaluated Vec
    can't be read back: constants become tracers under jit). FLOAT
    literals round through f32 first, matching `_align`'s cast chain."""
    while isinstance(e, (Alias, Cast)):
        e = e.children[0]
    if not (isinstance(e, Literal)
            and isinstance(e.value, (int, float))
            and not isinstance(e.value, bool)):
        return None
    if isinstance(dtype, T.FloatType):
        return float(np.float64(np.float32(e.value)))
    return float(e.value)


def _decimal_vs_float_scalar(data, scale: int, lit: float, op: str):
    """Integer-domain rewrite of ``f64(n / 10^scale) OP lit``.

    ``f64(n / 10^s)`` is monotone non-decreasing in the unscaled int n, so
    each comparison against a float scalar reduces to integer thresholds
    found by host binary search over exact host f64 — identical results to
    the CPU path, but only exact int64 compares run on device. Returns
    None when the rewrite doesn't apply (NaN literal keeps Spark's special
    NaN ordering on the float path)."""
    if np.isnan(lit):
        return None
    div = np.float64(10.0 ** scale)
    # the full unscaled int64 domain — values up to 2^63-1 are
    # representable decimals per types.py
    lo_b, hi_b = -(1 << 63), (1 << 63) - 1

    def first_n(pred) -> int:
        """Smallest n in [lo_b, hi_b] with pred(f64(n/10^s)) true; hi_b+1
        when none (pred is monotone in n)."""
        lo, hi = lo_b, hi_b + 1
        while lo < hi:
            mid = (lo + hi) // 2
            if pred(np.float64(mid) / div):
                hi = mid
            else:
                lo = mid + 1
        return lo

    n_ge = first_n(lambda v: v >= lit)   # first n with value >= lit
    n_gt = first_n(lambda v: v > lit)    # first n with value >  lit

    def at_least(n: int):
        """data >= n, handling the no-n-satisfies sentinel (n > hi_b)."""
        if n > hi_b:
            return jnp.zeros(np.shape(data), jnp.bool_)
        return data >= np.int64(n)

    if op == ">=":
        return at_least(n_ge)
    if op == ">":
        return at_least(n_gt)
    if op == "<":
        return ~at_least(n_ge)
    if op == "<=":
        return ~at_least(n_gt)
    if op == "=":
        return at_least(n_ge) & ~at_least(n_gt)
    if op == "!=":
        return ~at_least(n_ge) | at_least(n_gt)
    return None


def _dict_compare_table(dictionary: Optional[pa.Array], value: str, op: str):
    if dictionary is None:
        raise AnalysisError("string column without dictionary")
    ops = {"=": pc.equal, "!=": pc.not_equal, "<": pc.less,
           "<=": pc.less_equal, ">": pc.greater, ">=": pc.greater_equal}
    mask = ops[op](dictionary, pa.scalar(value)).to_numpy(zero_copy_only=False)
    return jnp.asarray(np.asarray(mask, dtype=np.bool_))


class EQ(BinaryComparison):
    op = "="

    def _cmp(self, l, r):
        return l == r


class NE(BinaryComparison):
    op = "!="

    def _cmp(self, l, r):
        return l != r


class LT(BinaryComparison):
    op = "<"

    def _cmp(self, l, r):
        return l < r


class LE(BinaryComparison):
    op = "<="

    def _cmp(self, l, r):
        return l <= r


class GT(BinaryComparison):
    op = ">"

    def _cmp(self, l, r):
        return l > r


class GE(BinaryComparison):
    op = ">="

    def _cmp(self, l, r):
        return l >= r


class EqNullSafe(BinaryComparison):
    """`<=>`: NULL <=> NULL is true, NULL <=> x is false — never returns
    NULL (reference: EqualNullSafe in predicates.scala)."""

    op = "<=>"

    def nullable(self, schema):
        return False

    def eval(self, batch: Batch) -> Vec:
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        if isinstance(lv.dtype, T.StringType) or \
                isinstance(rv.dtype, T.StringType):
            base = EQ(self.children[0], self.children[1]).eval(batch)
            both_null = self._both_null(lv, rv, np.shape(base.data))
            ok = base.data
            if base.validity is not None:
                ok = ok & base.validity
            return Vec(ok | both_null, T.BOOLEAN)
        out = T.common_type(lv.dtype, rv.dtype)
        l = _align(lv, out)
        r = _align(rv, out)
        eq = l == r
        lval = lv.validity if lv.validity is not None else \
            jnp.ones((), jnp.bool_)
        rval = rv.validity if rv.validity is not None else \
            jnp.ones((), jnp.bool_)
        both_valid = jnp.broadcast_to(lval & rval, np.shape(eq))
        both_null = self._both_null(lv, rv, np.shape(eq))
        return Vec((eq & both_valid) | both_null, T.BOOLEAN)

    @staticmethod
    def _both_null(lv, rv, shape):
        ln = ~lv.validity if lv.validity is not None else \
            jnp.zeros((), jnp.bool_)
        rn = ~rv.validity if rv.validity is not None else \
            jnp.zeros((), jnp.bool_)
        return jnp.broadcast_to(ln & rn, shape)

    def _cmp(self, l, r):
        raise AssertionError("EqNullSafe.eval is overridden")


class _DictStringTransform(Expression):
    """String function as a host-side dictionary rewrite: device codes
    are remapped once, per-row work is O(1) (SURVEY.md section 7,
    'Strings/varlen on TPU')."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def dtype(self, schema):
        return T.STRING

    def _transform(self, dictionary: pa.Array) -> pa.Array:
        raise NotImplementedError

    def eval(self, batch):
        from .columnar import apply_code_remap, dedupe_dictionary
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError(
                f"{type(self).__name__} requires dictionary-encoded strings")
        new_dict = self._transform(v.dictionary)
        if isinstance(new_dict, pa.ChunkedArray):
            new_dict = new_dict.combine_chunks()
        remap, uniq = dedupe_dictionary(new_dict)
        return Vec(apply_code_remap(v.data, remap), T.STRING, v.validity,
                   uniq)

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Upper(_DictStringTransform):
    def _transform(self, d):
        return pc.utf8_upper(d)


class Lower(_DictStringTransform):
    def _transform(self, d):
        return pc.utf8_lower(d)


class Trim(_DictStringTransform):
    def _transform(self, d):
        return pc.utf8_trim_whitespace(d)


class ConcatLit(_DictStringTransform):
    """concat with string literals around one string column (general
    column-column concat would need a product dictionary)."""

    def __init__(self, child: Expression, prefix: str = "", suffix: str = ""):
        super().__init__(child)
        self.prefix = prefix
        self.suffix = suffix

    def _transform(self, d):
        if d.type != pa.string():
            d = d.cast(pa.string())
        return pc.binary_join_element_wise(
            pa.array([self.prefix] * len(d)), d,
            pa.array([self.suffix] * len(d)), pa.scalar(""))

    def __repr__(self):
        return (f"concat({self.prefix!r}, {self.children[0]!r}, "
                f"{self.suffix!r})")


class StringLength(Expression):
    """length(str): a host dictionary lookup table, gathered by code."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def dtype(self, schema):
        return T.INT

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError("length requires dictionary-encoded strings")
        table = jnp.asarray(
            pc.utf8_length(v.dictionary).to_numpy(zero_copy_only=False)
            .astype(np.int32))
        data = jnp.take(table, jnp.clip(v.data, 0, table.shape[0] - 1))
        return Vec(data, T.INT, v.validity)

    def __repr__(self):
        return f"length({self.children[0]!r})"


class And(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        data = lv.data & rv.data
        if lv.validity is None and rv.validity is None:
            return Vec(data, T.BOOLEAN)
        # Kleene: false AND null = false
        lval = lv.validity if lv.validity is not None else True
        rval = rv.validity if rv.validity is not None else True
        false_l = (~lv.data) & (jnp.asarray(lval) if lv.validity is not None else True)
        false_r = (~rv.data) & (jnp.asarray(rval) if rv.validity is not None else True)
        validity = (jnp.asarray(lval) & jnp.asarray(rval)) | false_l | false_r
        return Vec(data & validity | jnp.zeros_like(data), T.BOOLEAN, validity)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        data = lv.data | rv.data
        if lv.validity is None and rv.validity is None:
            return Vec(data, T.BOOLEAN)
        lval = lv.validity if lv.validity is not None else True
        rval = rv.validity if rv.validity is not None else True
        true_l = lv.data & (jnp.asarray(lval) if lv.validity is not None else True)
        true_r = rv.data & (jnp.asarray(rval) if rv.validity is not None else True)
        validity = (jnp.asarray(lval) & jnp.asarray(rval)) | true_l | true_r
        return Vec(data, T.BOOLEAN, validity)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    def __init__(self, child):
        self.children = (child,)

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        v = self.children[0].eval(batch)
        return Vec(~v.data, T.BOOLEAN, v.validity)

    def __repr__(self):
        return f"(NOT {self.children[0]!r})"


class IsNull(Expression):
    def __init__(self, child):
        self.children = (child,)

    def dtype(self, schema):
        return T.BOOLEAN

    def nullable(self, schema):
        return False

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.validity is None:
            return Vec(jnp.zeros(np.shape(v.data) or (1,), dtype=jnp.bool_)
                       if v.data is not None else jnp.zeros((), jnp.bool_),
                       T.BOOLEAN)
        return Vec(~v.validity, T.BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} IS NULL)"


class In(Expression):
    def __init__(self, child: Expression, values: Tuple):
        self.children = (child,)
        self.values = tuple(values)

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if isinstance(v.dtype, T.StringType):
            if v.dictionary is None:
                raise AnalysisError("IN on string requires dictionary")
            mask = pc.is_in(v.dictionary,
                            value_set=pa.array(list(self.values))) \
                .to_numpy(zero_copy_only=False)
            table = jnp.asarray(np.asarray(mask, dtype=np.bool_))
            data = jnp.take(table, jnp.clip(v.data, 0, len(table) - 1))
            return Vec(data, T.BOOLEAN, v.validity)
        acc = None
        for val in self.values:
            lit = cast_vec(Literal(val).eval_scalar(), v.dtype)
            hit = v.data == lit.data
            acc = hit if acc is None else (acc | hit)
        return Vec(acc, T.BOOLEAN, v.validity)

    def __repr__(self):
        return f"({self.children[0]!r} IN {self.values!r})"


class Like(Expression):
    """LIKE with SQL wildcards, evaluated on the host dictionary then
    gathered by code — O(|dict|) host work regardless of row count."""

    def __init__(self, child: Expression, pattern: str):
        self.children = (child,)
        self.pattern = pattern

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError("LIKE requires a dictionary-encoded string column")
        mask = pc.match_like(v.dictionary, self.pattern).to_numpy(
            zero_copy_only=False)
        table = jnp.asarray(np.asarray(mask, dtype=np.bool_))
        data = jnp.take(table, jnp.clip(v.data, 0, len(table) - 1))
        return Vec(data, T.BOOLEAN, v.validity)

    def __repr__(self):
        return f"({self.children[0]!r} LIKE {self.pattern!r})"


class Substring(Expression):
    """substring(col, start, len) on dictionary strings: rewrites the
    host dictionary; device codes are unchanged (a dictionary transform)."""

    def __init__(self, child: Expression, start: int, length: int):
        self.children = (child,)
        self.start = start
        self.length = length

    def dtype(self, schema):
        return T.STRING

    def eval(self, batch):
        from .columnar import apply_code_remap, dedupe_dictionary
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError("substring requires dictionary-encoded strings")
        new_dict = pc.utf8_slice_codeunits(
            v.dictionary, start=self.start - 1,
            stop=self.start - 1 + self.length)
        # distinct old values can slice to one new value: dedupe the new
        # dictionary and remap device codes so equal strings share a code
        # (group-by/join compare codes directly)
        remap, uniq = dedupe_dictionary(
            new_dict.combine_chunks()
            if isinstance(new_dict, pa.ChunkedArray) else new_dict)
        return Vec(apply_code_remap(v.data, remap), T.STRING, v.validity, uniq)

    def __repr__(self):
        return f"substring({self.children[0]!r},{self.start},{self.length})"


class Coalesce(Expression):
    """First non-NULL argument (reference: nullExpressions.scala Coalesce)."""

    def __init__(self, *children: Expression):
        if not children:
            raise AnalysisError("coalesce requires at least one argument")
        self.children = tuple(children)

    def dtype(self, schema):
        out = self.children[0].dtype(schema)
        for c in self.children[1:]:
            out = T.common_type(out, c.dtype(schema))
        return out

    def nullable(self, schema):
        return all(c.nullable(schema) for c in self.children)

    def eval(self, batch):
        out_dtype = self.dtype(batch.schema())
        if isinstance(out_dtype, T.StringType):
            return self._eval_string(batch)
        acc = cast_vec(self.children[0].eval(batch), out_dtype)
        data, validity = acc.data, acc.validity
        for c in self.children[1:]:
            if validity is None:
                break
            v = cast_vec(c.eval(batch), out_dtype)
            vval = v.validity if v.validity is not None else \
                jnp.ones((), jnp.bool_)
            data = jnp.where(validity, data, v.data)
            validity = validity | jnp.broadcast_to(vval, np.shape(validity))
        return Vec(data, out_dtype, validity)

    def _eval_string(self, batch):
        from .columnar import unify_string_columns
        acc = self.children[0].eval(batch)
        data, validity, dictionary = acc.data, acc.validity, acc.dictionary
        for c in self.children[1:]:
            if validity is None:
                break
            v = c.eval(batch)
            if v.data is None and isinstance(c, Literal) \
                    and isinstance(c.value, str):
                # host-scalar string literal -> singleton dictionary
                v = Vec(jnp.zeros(np.shape(data), jnp.int32), T.STRING,
                        None, pa.array([c.value]))
            if dictionary is None or v.dictionary is None:
                raise AnalysisError("coalesce on strings requires dictionaries")
            data, v_data, dictionary = unify_string_columns(
                data, dictionary, v.data, v.dictionary)
            vval = v.validity if v.validity is not None else \
                jnp.ones((), jnp.bool_)
            data = jnp.where(validity, data, v_data)
            validity = validity | jnp.broadcast_to(vval, np.shape(validity))
        return Vec(data, T.STRING, validity, dictionary)

    def __repr__(self):
        return f"coalesce({', '.join(repr(c) for c in self.children)})"


class CaseWhen(Expression):
    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 otherwise: Optional[Expression] = None):
        self.branches = [(c, v) for c, v in branches]
        self.otherwise = otherwise
        flat: List[Expression] = []
        for c, v in self.branches:
            flat += [c, v]
        if otherwise is not None:
            flat.append(otherwise)
        self.children = tuple(flat)

    def map_children(self, f):
        # branches/otherwise are views over `children`; the base
        # copy-and-replace would leave them pointing at stale nodes
        # (eval reads self.branches, not self.children)
        new_kids = [f(c) for c in self.children]
        n = len(self.branches)
        branches = [(new_kids[2 * i], new_kids[2 * i + 1]) for i in range(n)]
        otherwise = new_kids[2 * n] if self.otherwise is not None else None
        return CaseWhen(branches, otherwise)

    def dtype(self, schema):
        dts = [v.dtype(schema) for _, v in self.branches]
        if self.otherwise is not None:
            dts.append(self.otherwise.dtype(schema))
        out = dts[0]
        for d in dts[1:]:
            out = T.common_type(out, d)
        return out

    def _eval_string(self, batch):
        """CASE producing strings from LITERAL branches: the branch
        values become the dictionary and codes select by condition
        (string columns in branches would need dictionary unification —
        unsupported)."""
        import pyarrow as pa
        vals = []
        for _c, v in self.branches:
            if not (isinstance(v, Literal)
                    and (v.value is None or isinstance(v.value, str))):
                raise AnalysisError(
                    "CASE with string results supports literal branch "
                    "values only")
            vals.append(v.value)
        if self.otherwise is not None:
            if not (isinstance(self.otherwise, Literal)
                    and (self.otherwise.value is None
                         or isinstance(self.otherwise.value, str))):
                raise AnalysisError(
                    "CASE with string results supports a literal ELSE "
                    "only")
            vals.append(self.otherwise.value)
        else:
            vals.append(None)
        else_code = len(vals) - 1
        codes = jnp.full((batch.capacity,), else_code, jnp.int32)
        for i, (cond, _v) in reversed(list(enumerate(self.branches))):
            cv = cond.eval(batch)
            cond_true = cv.data
            if cv.validity is not None:
                cond_true = cond_true & cv.validity
            codes = jnp.where(cond_true, jnp.int32(i), codes)
        dictionary = pa.array([v if v is not None else "" for v in vals],
                              type=pa.string())
        null_codes = [i for i, v in enumerate(vals) if v is None]
        validity = None
        if null_codes:
            validity = jnp.ones((batch.capacity,), jnp.bool_)
            for nc in null_codes:
                validity = validity & (codes != nc)
        return Vec(codes, T.STRING, validity, dictionary)

    def eval(self, batch):
        out_dtype = self.dtype(batch.schema())
        if isinstance(out_dtype, T.StringType):
            return self._eval_string(batch)
        if self.otherwise is not None:
            acc = cast_vec(self.otherwise.eval(batch), out_dtype)
            acc_data, acc_val = acc.data, acc.validity
        else:
            acc_data = jnp.zeros((), out_dtype.np_dtype)
            acc_val = jnp.zeros((), jnp.bool_)
        for cond, val in reversed(self.branches):
            cv = cond.eval(batch)
            vv = cast_vec(val.eval(batch), out_dtype)
            cond_true = cv.data
            if cv.validity is not None:
                cond_true = cond_true & cv.validity
            acc_data = jnp.where(cond_true, vv.data, acc_data)
            if vv.validity is not None or acc_val is not None:
                vval = vv.validity if vv.validity is not None else \
                    jnp.ones((), jnp.bool_)
                aval = acc_val if acc_val is not None else jnp.ones((), jnp.bool_)
                acc_val = jnp.where(cond_true, vval, aval)
        acc_val = None if acc_val is None else jnp.broadcast_to(
            acc_val, np.shape(acc_data))
        return Vec(acc_data, out_dtype, acc_val)

    def __repr__(self):
        return f"CASE {self.branches!r} ELSE {self.otherwise!r}"

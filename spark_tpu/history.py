"""Event-log replay: the HistoryServer analog, sized to this engine.

The reference persists a typed event stream (`EventLoggingListener.scala`)
and rebuilds UI state by replay (`HistoryServer.scala:50` +
`ReplayListenerBus`). Here each query execution appends one JSON line
(plan fingerprint, phase timings, per-operator metrics) and replay is a
DataFrame over those lines — queryable with the engine itself or pandas.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

import pandas as pd


def read_event_log(log_dir: str, app: Optional[str] = None) -> pd.DataFrame:
    """All logged query executions as a flat DataFrame (one row per
    execution: ts, plan, per-phase seconds, metric columns)."""
    pattern = os.path.join(log_dir, f"app-{app or '*'}.jsonl")
    rows: List[dict] = []
    for path in sorted(glob.glob(pattern)):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                row = {"ts": e.get("ts"), "plan": e.get("plan"),
                       "app": os.path.basename(path)}
                for k, v in (e.get("phase_times_s") or {}).items():
                    row[f"phase_{k}_s"] = v
                for k, v in (e.get("metrics") or {}).items():
                    row[k] = v
                rows.append(row)
    return pd.DataFrame(rows)


def runtime_filter_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, filter) runtime-filter pruning summary from a
    read_event_log frame: tag, rows tested, rows pruned, pruning ratio
    and the trace-time build cost — the observability surface of the
    runtime-filter subsystem (rtf_* metrics emitted by
    RuntimeFilterExec)."""
    rows: List[dict] = []
    tested_cols = [c for c in events.columns
                   if c.startswith("rtf_tested_")]
    for _, r in events.iterrows():
        for c in tested_cols:
            tag = c[len("rtf_tested_"):]
            tested = r.get(c)
            if pd.isna(tested):
                continue
            pruned = r.get(f"rtf_pruned_{tag}")
            rows.append({
                "ts": r.get("ts"),
                "app": r.get("app"),
                "tag": tag,
                "tested": int(tested),
                "pruned": None if pd.isna(pruned) else int(pruned),
                # None (not 0.0) when the pruned metric is absent:
                # "unknown" must not read as "pruned nothing"
                "ratio": (float(pruned) / float(tested)
                          if not pd.isna(pruned) and tested else None),
                "build_ms": r.get(f"rtf_build_ms_{tag}"),
            })
    return pd.DataFrame(rows)

"""Event-log replay: the HistoryServer analog, sized to this engine.

The reference persists a typed event stream (`EventLoggingListener.scala`)
and rebuilds UI state by replay (`HistoryServer.scala:50` +
`ReplayListenerBus`). Here each query execution appends one JSON line
(plan fingerprint, phase timings, per-operator metrics) and replay is a
DataFrame over those lines — queryable with the engine itself or pandas.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

import pandas as pd


def read_event_log(log_dir: str, app: Optional[str] = None) -> pd.DataFrame:
    """All logged query executions as a flat DataFrame (one row per
    execution: ts, plan, per-phase seconds, metric columns)."""
    pattern = os.path.join(log_dir, f"app-{app or '*'}.jsonl")
    rows: List[dict] = []
    for path in sorted(glob.glob(pattern)):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                row = {"ts": e.get("ts"), "plan": e.get("plan"),
                       "app": os.path.basename(path)}
                for k, v in (e.get("phase_times_s") or {}).items():
                    row[f"phase_{k}_s"] = v
                for k, v in (e.get("metrics") or {}).items():
                    row[k] = v
                rows.append(row)
    return pd.DataFrame(rows)

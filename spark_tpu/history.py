"""Event-log replay: the HistoryServer analog, sized to this engine.

The reference persists a typed event stream (`EventLoggingListener.scala`)
and rebuilds UI state by replay (`HistoryServer.scala:50` +
`ReplayListenerBus`). Here each query execution appends one JSON line
(plan fingerprint, phase timings, per-operator metrics) and replay is a
DataFrame over those lines — queryable with the engine itself or pandas.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Tuple

import pandas as pd

#: basename shape of event-log files: app-<stem>.jsonl (live) and
#: app-<stem>.<N>.jsonl (rolled by eventLog.maxBytes)
_LOG_NAME = re.compile(r"^app-(?P<stem>.+?)(?:\.(?P<n>\d+))?\.jsonl$")


def _log_paths(log_dir: str, app: Optional[str]) -> List[str]:
    """Event-log files in replay order: per app stem, rolled files in
    roll-index order, the live (unsuffixed) file last — so a rotated
    log replays its lines in write order."""
    entries: List[Tuple[str, int, str]] = []
    for path in glob.glob(os.path.join(log_dir, "app-*.jsonl")):
        m = _LOG_NAME.match(os.path.basename(path))
        if m is None:
            continue
        stem, n = m.group("stem"), m.group("n")
        if app is not None and stem != app:
            continue
        # live file sorts after every rolled index
        entries.append((stem, int(n) if n is not None else 1 << 62, path))
    return [p for _, _, p in sorted(entries)]


#: event fields kept nested (object columns) rather than flattened
_NESTED = ("spans", "stages", "shards", "predictions",
           "analysis_findings", "plan_tree", "reorder", "streaming",
           "udf", "trigger", "rule_trace")


def read_event_log(log_dir: str, app: Optional[str] = None) -> pd.DataFrame:
    """All logged query executions as a flat DataFrame (one row per
    execution: ts, plan, status, per-phase seconds, metric columns,
    plus nested `spans`/`stages` object columns when logged)."""
    rows: List[dict] = []
    for path in _log_paths(log_dir, app):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                row = {"ts": e.get("ts"), "plan": e.get("plan"),
                       "app": os.path.basename(path)}
                for k in ("query_id", "status", "schema_version",
                          "device_hbm_capacity_bytes", "error"):
                    if k in e:
                        row[k] = e[k]
                for k in _NESTED:
                    if k in e:
                        row[k] = e[k]
                for k, v in (e.get("phase_times_s") or {}).items():
                    row[f"phase_{k}_s"] = v
                for k, v in (e.get("metrics") or {}).items():
                    row[k] = v
                for k, v in (e.get("fault_summary") or {}).items():
                    # recovery counters flatten to fault_* columns; the
                    # per-event record list stays nested
                    row[f"fault_{k}"] = v
                rows.append(row)
    return pd.DataFrame(rows)


#: recovery-action counters an execution's fault_summary may carry
#: (executor._record_fault actions + the aggregate backoff total).
#: chunk_retry / stage_reuse / checkpoint_restore are the
#: partial-progress actions (execution/recovery.py); mesh_restart /
#: decommission / shard_rebalance are the elastic-mesh actions
#: (parallel/elastic.py); cancel marks a query stopped by lifecycle
#: control — cancellation or a blown queryDeadlineMs
#: (execution/lifecycle.py).
FAULT_ACTIONS = ("transient_retry", "stage_timeout", "oom_cache_evict",
                 "oom_spill_reroute", "mesh_fallback", "chunk_retry",
                 "stage_reuse", "checkpoint_restore", "mesh_restart",
                 "decommission", "shard_rebalance", "cancel")


def fault_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-execution failure-recovery summary from a read_event_log
    frame: one row per execution that survived at least one fault, with
    the count of each recovery action (retries, cache evictions, spill
    reroutes, mesh fallbacks, stage timeouts), the total backoff slept,
    and the bounded per-fault event records — the observability surface
    of the degradation ladder (execution/failures.py)."""
    rows: List[dict] = []
    cols = [c for c in events.columns if c.startswith("fault_")]
    if not cols:
        return pd.DataFrame(rows)

    def present(v) -> bool:
        if isinstance(v, (list, dict)):
            return True  # nested event records (pd.isna chokes on lists)
        return not pd.isna(v)

    for _, r in events.iterrows():
        acted = {c: r.get(c) for c in cols if present(r.get(c))}
        if not any(c != "fault_events" for c in acted):
            continue
        row = {"ts": r.get("ts"), "app": r.get("app")}
        for a in FAULT_ACTIONS:
            v = acted.get(f"fault_{a}")
            row[a] = 0 if v is None else int(v)
        bk = acted.get("fault_retry_backoff_ms")
        row["retry_backoff_ms"] = 0.0 if bk is None else float(bk)
        # events past the executor's 32-record cap are dropped from the
        # nested list but COUNTED — nonzero means `events` is truncated
        ed = acted.get("fault_events_dropped")
        row["events_dropped"] = 0 if ed is None else int(ed)
        row["events"] = acted.get("fault_events") or []
        rows.append(row)
    return pd.DataFrame(rows)


def stage_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, span) lifecycle timing from a read_event_log
    frame: one row per recorded span (analysis/optimize/plan/compile/
    ingest/dispatch/retries), with start offset and duration — the
    stage-timeline view of the SQL UI, as a DataFrame."""
    rows: List[dict] = []
    if "spans" not in events.columns:
        return pd.DataFrame(rows)
    for _, r in events.iterrows():
        spans = r.get("spans")
        if not isinstance(spans, list):
            continue
        for s in spans:
            rows.append({"ts": r.get("ts"), "app": r.get("app"),
                         "query_id": r.get("query_id"),
                         "span": s.get("name"),
                         "t0_ms": s.get("t0_ms"),
                         "dur_ms": s.get("dur_ms"),
                         "attrs": s.get("attrs") or {}})
    return pd.DataFrame(rows)


def compile_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, compiled stage) XLA cost accounting: flops,
    bytes accessed, argument/output/temp sizes, peak HBM demand and
    the analysis-compile cost — from the `stages` records the executor
    captures via cost_analysis()/memory_analysis()."""
    rows: List[dict] = []
    if "stages" not in events.columns:
        return pd.DataFrame(rows)
    for _, r in events.iterrows():
        stages = r.get("stages")
        if not isinstance(stages, list):
            continue
        for s in stages:
            rows.append({"ts": r.get("ts"), "app": r.get("app"),
                         "query_id": r.get("query_id"),
                         "stage": s.get("key_hash"),
                         "flops": s.get("flops"),
                         "bytes_accessed": s.get("bytes_accessed"),
                         "argument_bytes": s.get("argument_bytes"),
                         "output_bytes": s.get("output_bytes"),
                         "temp_bytes": s.get("temp_bytes"),
                         "peak_hbm_bytes": s.get("peak_hbm_bytes"),
                         "analysis_ms": s.get("analysis_ms")})
    return pd.DataFrame(rows)


def hbm_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-execution HBM headroom: the max per-stage peak demand
    (memory_analysis) against the device capacity when known — the
    'how close was this query to RESOURCE_EXHAUSTED' view the OOM
    ladder is tuned from."""
    rows: List[dict] = []
    if "stages" not in events.columns:
        return pd.DataFrame(rows)
    for _, r in events.iterrows():
        stages = r.get("stages")
        if not isinstance(stages, list):
            continue
        peaks = [s.get("peak_hbm_bytes") for s in stages
                 if s.get("peak_hbm_bytes") is not None]
        if not peaks:
            continue
        peak = max(peaks)
        worst = next(s for s in stages
                     if s.get("peak_hbm_bytes") == peak)
        cap = r.get("device_hbm_capacity_bytes")
        cap = None if pd.isna(cap) else int(cap)
        rows.append({"ts": r.get("ts"), "app": r.get("app"),
                     "query_id": r.get("query_id"),
                     "plan": r.get("plan"),
                     "n_stages": len(stages),
                     "peak_hbm_bytes": int(peak),
                     "peak_stage": worst.get("key_hash"),
                     "argument_bytes": worst.get("argument_bytes"),
                     "temp_bytes": worst.get("temp_bytes"),
                     "output_bytes": worst.get("output_bytes"),
                     "capacity_bytes": cap,
                     "headroom_ratio": (round(peak / cap, 4)
                                        if cap else None)})
    return pd.DataFrame(rows)


def streaming_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-micro-batch lifecycle from a read_event_log frame: one row
    per `streaming` record (schema v4) — batch id, offset range, rows
    in/out, state persistence kind (delta vs snapshot) and bytes,
    changed groups, quarantined files, sink parts and wall time — and
    one row per `trigger` record (schema v6, record='trigger') — tick
    id, wall-clock skew, batches run, supervisor restarts and
    reconnects. The replay surface of the durable-streaming tier
    (streaming.py + execution/state_store.py); the incremental-
    checkpointing claim (steady-state delta bytes << snapshot bytes)
    and the unattended-operation story (reconnects, restarts, skew)
    are both checkable straight off this frame."""
    rows: List[dict] = []
    for _, r in events.iterrows():
        s = r.get("streaming") \
            if "streaming" in events.columns else None
        if isinstance(s, dict):
            rows.append({"ts": r.get("ts"), "app": r.get("app"),
                         "query_id": r.get("query_id"),
                         "record": "batch",
                         "batch_id": s.get("batch_id"),
                         "start": s.get("start"), "end": s.get("end"),
                         "rows_in": s.get("rows_in"),
                         "rows_out": s.get("rows_out"),
                         "kind": s.get("kind"),
                         "state_bytes": s.get("state_bytes"),
                         "changed_groups": s.get("changed_groups"),
                         "quarantined": s.get("quarantined"),
                         "sink_parts": s.get("sink_parts"),
                         "source": s.get("source"),
                         "wall_ms": s.get("wall_ms")})
        t = r.get("trigger") if "trigger" in events.columns else None
        if isinstance(t, dict):
            rows.append({"ts": r.get("ts"), "app": r.get("app"),
                         "query_id": r.get("query_id"),
                         "record": "trigger",
                         "tick": t.get("tick"),
                         "skew_ms": t.get("skew_ms"),
                         "batches_run": t.get("batches_run"),
                         "restarts": t.get("restarts"),
                         "reconnects": t.get("reconnects"),
                         "source": t.get("source")})
    return pd.DataFrame(rows)


def shard_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, shard, chunk) telemetry from a read_event_log
    frame: one row per flight-recorder record (schema v3 `shards`) —
    shard id, host, chunk index, phase (ingest/compute/transfer),
    rows, bytes, dispatch duration and the per-shard completion wait.
    The per-shard stage-timeline view the elastic-mesh rebalancer (and
    straggler_report below) consumes."""
    rows: List[dict] = []
    if "shards" not in events.columns:
        return pd.DataFrame(rows)
    for _, r in events.iterrows():
        recs = r.get("shards")
        if not isinstance(recs, list):
            continue
        for s in recs:
            rows.append({"ts": r.get("ts"), "app": r.get("app"),
                         "query_id": r.get("query_id"),
                         "shard": s.get("shard"), "host": s.get("host"),
                         "chunk": s.get("chunk"), "phase": s.get("phase"),
                         "source": s.get("source"),
                         "rows": s.get("rows"), "bytes": s.get("bytes"),
                         "dur_ms": s.get("dur_ms"),
                         "wait_ms": s.get("wait_ms")})
    return pd.DataFrame(rows)


def straggler_report(events: pd.DataFrame, factor: Optional[float] = None,
                     min_chunks: Optional[int] = None,
                     min_latency_ms: Optional[float] = None
                     ) -> pd.DataFrame:
    """Offline straggler detection over a replayed event log: the live
    StragglerMonitor's detection math (rolling-WINDOW medians per
    shard, baseline = median of qualified shards' medians, factor
    threshold over the minLatencyMs floor) applied to the logged
    per-shard compute waits — one row per (execution, shard).

    Caveat vs the live verdict: thresholds default to the conf
    REGISTRY values — a logged session's runtime overrides are not in
    the log, so pass the session's factor/minChunks/minLatencyMs
    explicitly to reproduce its live verdicts. Shards with fewer than
    min_chunks samples are reported but excluded from the baseline and
    never flagged (the live monitor's `ready` gate — the detection
    rule itself is the SHARED `evaluate_waits`, so the two
    implementations cannot drift)."""
    from .config import Conf
    from .observability.straggler import WINDOW, evaluate_waits
    conf = Conf()
    factor = float(conf.get("spark_tpu.sql.straggler.factor")) \
        if factor is None else float(factor)
    min_chunks = int(conf.get("spark_tpu.sql.straggler.minChunks")) \
        if min_chunks is None else int(min_chunks)
    floor_ms = float(conf.get("spark_tpu.sql.straggler.minLatencyMs")) \
        if min_latency_ms is None else float(min_latency_ms)
    shards = shard_summary(events)
    rows: List[dict] = []
    if shards.empty:
        return pd.DataFrame(rows)
    compute = shards[(shards["phase"] == "compute")
                     & shards["shard"].notna()]
    for (app, qid), grp in compute.groupby(["app", "query_id"],
                                           dropna=False):
        per_shard = {}
        hosts = {}
        for shard, g in grp.groupby("shard"):
            # the live monitor's rolling window: the LAST
            # max(WINDOW, min_chunks) waits in chunk order, so long
            # streams judge recent behavior, not ancient warmup chunks
            # (and a large min_chunks widens the window rather than
            # making the ready gate unsatisfiable)
            g = g.sort_values("chunk")
            waits = [float(w) for w in g["wait_ms"]
                     if not pd.isna(w)][-max(WINDOW, min_chunks):]
            if not waits:
                continue
            per_shard[int(shard)] = waits
            hosts[int(shard)] = g["host"].iloc[0]
        medians, baseline, flag_now = evaluate_waits(
            per_shard, factor, min_chunks, floor_ms)
        for shard, med in sorted(medians.items()):
            rows.append({
                "app": app, "query_id": qid, "shard": shard,
                "host": hosts.get(shard),
                "chunks": len(per_shard[shard]),
                "median_wait_ms": round(med, 3),
                "baseline_ms": (round(baseline, 3)
                                if baseline is not None else None),
                "ratio": (round(med / baseline, 3)
                          if baseline else None),
                "flagged": shard in flag_now})
    return pd.DataFrame(rows)


#: prediction kind -> observed traced-metric column pattern
_PRED_OBSERVED = {"exch_rows": "exch_rows_{tag}",
                  "exch_bytes": "exch_bytes_{tag}",
                  "join_rows": "join_rows_{tag}",
                  "agg_groups": "agg_groups_{tag}",
                  # worker-lane UDF traffic: untagged counters, so the
                  # pattern is the metric name itself (schema v5 also
                  # mirrors them in the nested `udf` record)
                  "udf_rows": "udf_rows",
                  "udf_batches": "udf_batches"}


def grade_predictions(predictions, metrics) -> List[dict]:
    """Grade plan-time size predictions (analysis/predictions.py)
    against one execution's observed metrics dict. hit = the bound
    held without gross waste (obs <= pred <= 4*obs); under = the
    prediction was exceeded (an AQE overflow / undersized filter);
    over = more than 4x slack (wasted capacity/HBM). Shared by
    history.prediction_report (event-log replay) and the bench
    `tpch_*_pred_err_pct` sidecar (live qe)."""
    out: List[dict] = []
    for p in predictions or []:
        kind, tag = p.get("kind"), p.get("tag")
        pattern = _PRED_OBSERVED.get(kind)
        if pattern is None or tag is None:
            continue
        obs = metrics.get(pattern.format(tag=tag))
        if obs is None:
            continue
        try:
            obs = float(obs)
            pred = float(p.get("predicted"))
        except (TypeError, ValueError):
            continue
        if obs <= 0:
            grade = "hit" if pred <= 8 else "over"
            err = None
        else:
            err = round((pred - obs) / obs * 100.0, 1)
            grade = ("under" if pred < obs
                     else "hit" if pred <= 4 * obs else "over")
        out.append({"kind": kind, "tag": tag, "basis": p.get("basis"),
                    "predicted": int(pred), "observed": int(obs),
                    "err_pct": err, "grade": grade})
    return out


#: finding codes whose detail carries a byte/row bound gradeable
#: against observables: code -> (detail key, what it bounds)
_FINDING_BOUNDS = {
    "MESH_FULL_REPLICATION": ("replicated_bytes_bound", "exch_bytes"),
    "MESH_GATHER_RESULT": ("replicated_bytes_bound", "exch_bytes"),
    "JOIN_HASH_TABLE_PRESSURE": ("table_bytes", "peak_hbm"),
    "SPILL_HOST_SYNC": ("estimated_bytes", "peak_hbm"),
}


def prediction_report(events: pd.DataFrame) -> pd.DataFrame:
    """Analyzer/planner self-grading over a replayed event log: every
    logged prediction joined against the observed metric of the same
    tag, plus analyzer findings whose details carry byte bounds graded
    against observed exchange bytes and stage peak-HBM. One row per
    graded prediction with hit/over/under and signed error percent."""
    rows: List[dict] = []
    metric_skip = ("ts", "plan", "app", "query_id", "status",
                   "schema_version")
    for _, r in events.iterrows():
        metrics = {c: r[c] for c in events.columns
                   if c not in metric_skip and c not in _NESTED
                   and not isinstance(r[c], (list, dict))
                   and pd.notna(r[c])}
        u = r.get("udf") if "udf" in events.columns else None
        if isinstance(u, dict):
            # the nested `udf` record (schema v5) carries the same
            # totals as the udf_* counters; merge them in (counters
            # win) so udf_batches/udf_rows predictions grade even on
            # logs where the metrics channel was trimmed
            for rec_key, col in (("batches", "udf_batches"),
                                 ("rows", "udf_rows")):
                v = u.get(rec_key)
                if v is not None and col not in metrics:
                    metrics[col] = v
        base = {"ts": r.get("ts"), "app": r.get("app"),
                "query_id": r.get("query_id")}
        preds = r.get("predictions") if "predictions" in events.columns \
            else None
        if isinstance(preds, list):
            for g in grade_predictions(preds, metrics):
                rows.append(dict(base, **g))
        finds = r.get("analysis_findings") \
            if "analysis_findings" in events.columns else None
        stages = r.get("stages") if "stages" in events.columns else None
        peak = None
        if isinstance(stages, list):
            peaks = [s.get("peak_hbm_bytes") for s in stages
                     if s.get("peak_hbm_bytes") is not None]
            peak = max(peaks) if peaks else None
        if isinstance(finds, list):
            for f in finds:
                rows.extend(_grade_finding(f, metrics, peak, base))
    return pd.DataFrame(rows)


def rule_report(events: pd.DataFrame) -> pd.DataFrame:
    """Optimizer-rule activity over a replayed event log (schema v7
    `rule_trace`): one row per (execution, batch, rule) that was
    INVOKED, with invocation/effective counts, total rule ms, and the
    execution's PLAN_INTEGRITY finding count — the replay surface for
    'which rewrites actually fire, how often, at what cost, and did
    the verifier ever object'."""
    rows: List[dict] = []
    if "rule_trace" not in events.columns:
        return pd.DataFrame(rows)
    for _, r in events.iterrows():
        trace = r.get("rule_trace")
        if not isinstance(trace, list):
            continue
        finds = r.get("analysis_findings") \
            if "analysis_findings" in events.columns else None
        integrity = sum(1 for f in finds or []
                        if isinstance(f, dict)
                        and f.get("code") == "PLAN_INTEGRITY") \
            if isinstance(finds, list) else 0
        base = {"ts": r.get("ts"), "app": r.get("app"),
                "query_id": r.get("query_id"),
                "integrity_findings": integrity}
        for rec in trace:
            if not isinstance(rec, dict):
                continue
            rows.append(dict(
                base, batch=rec.get("batch"), rule=rec.get("rule"),
                invocations=rec.get("invocations"),
                effective=rec.get("effective"), ms=rec.get("ms"),
                traced_diff="diff" in rec))
    return pd.DataFrame(rows)


def _grade_finding(f: dict, metrics: dict, peak_hbm, base: dict
                   ) -> List[dict]:
    spec = _FINDING_BOUNDS.get(f.get("code"))
    if spec is None:
        return []
    key, target = spec
    pred = (f.get("detail") or {}).get(key)
    if pred is None:
        return []
    if target == "peak_hbm":
        obs = peak_hbm
        tag = f.get("op")
    else:
        # op is "ExchangeExec[e1]" — observed metric keys on the tag
        op = str(f.get("op") or "")
        tag = op[op.find("[") + 1:op.rfind("]")] \
            if "[" in op and "]" in op else None
        obs = metrics.get(f"exch_bytes_{tag}") if tag else None
    if obs is None:
        return []
    obs, pred = float(obs), float(pred)
    err = round((pred - obs) / obs * 100.0, 1) if obs > 0 else None
    # findings state upper BOUNDS: holding (obs <= pred) is a hit even
    # with slack; an exceeded bound is the miss that matters
    grade = "under" if pred < obs else "hit"
    return [dict(base, kind=f"finding:{f.get('code')}", tag=tag,
                 basis=key, predicted=int(pred), observed=int(obs),
                 err_pct=err, grade=grade)]


def compare_runs(base: pd.DataFrame, other: pd.DataFrame,
                 on: str = "plan") -> pd.DataFrame:
    """Compare two read_event_log frames (e.g. two BENCH rounds, or
    before/after a conf change): for each key present in both, the
    LAST execution's numeric columns side by side with delta and
    ratio. The regression-hunting view of the replay store."""
    rows: List[dict] = []
    if base.empty or other.empty or on not in base.columns \
            or on not in other.columns:
        return pd.DataFrame(rows)
    # whole last ROW per key — groupby().last() would take the last
    # NON-NULL per column, splicing values from different executions
    b_last = base.drop_duplicates(subset=[on], keep="last").set_index(on)
    o_last = other.drop_duplicates(subset=[on], keep="last").set_index(on)
    numeric = [c for c in b_last.columns
               if c in o_last.columns
               and pd.api.types.is_numeric_dtype(b_last[c])
               and pd.api.types.is_numeric_dtype(o_last[c])]
    for key in b_last.index.intersection(o_last.index):
        for c in numeric:
            bv, ov = b_last.at[key, c], o_last.at[key, c]
            if pd.isna(bv) and pd.isna(ov):
                continue
            rows.append({
                on: key, "column": c,
                "base": None if pd.isna(bv) else float(bv),
                "other": None if pd.isna(ov) else float(ov),
                "delta": (None if pd.isna(bv) or pd.isna(ov)
                          else float(ov) - float(bv)),
                "ratio": (None if pd.isna(bv) or pd.isna(ov) or not bv
                          else round(float(ov) / float(bv), 4))})
    return pd.DataFrame(rows)


def runtime_filter_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, filter) runtime-filter pruning summary from a
    read_event_log frame: tag, rows tested, rows pruned, pruning ratio
    and the trace-time build cost — the observability surface of the
    runtime-filter subsystem (rtf_* metrics emitted by
    RuntimeFilterExec)."""
    rows: List[dict] = []
    tested_cols = [c for c in events.columns
                   if c.startswith("rtf_tested_")]
    for _, r in events.iterrows():
        for c in tested_cols:
            tag = c[len("rtf_tested_"):]
            tested = r.get(c)
            if pd.isna(tested):
                continue
            pruned = r.get(f"rtf_pruned_{tag}")
            rows.append({
                "ts": r.get("ts"),
                "app": r.get("app"),
                "tag": tag,
                "tested": int(tested),
                "pruned": None if pd.isna(pruned) else int(pruned),
                # None (not 0.0) when the pruned metric is absent:
                # "unknown" must not read as "pruned nothing"
                "ratio": (float(pruned) / float(tested)
                          if not pd.isna(pruned) and tested else None),
                "build_ms": r.get(f"rtf_build_ms_{tag}"),
            })
    return pd.DataFrame(rows)


def status_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Offline replay of the live status store: the per-app health
    view `GET /status` serves, rebuilt from a read_event_log frame —
    one row per app with per-status outcome counts, cumulative
    per-phase seconds, and end-to-end latency percentiles (sum of the
    phase_*_s columns per execution, in ms). Rows with no phase data
    (streaming/trigger lines) are excluded: they are lifecycle
    records, not query executions."""
    rows: List[dict] = []
    phase_cols = [c for c in events.columns
                  if c.startswith("phase_") and c.endswith("_s")]
    if not phase_cols or "app" not in events.columns:
        return pd.DataFrame(rows)
    execs = events[events[phase_cols].notna().any(axis=1)].copy()
    if execs.empty:
        return pd.DataFrame(rows)
    execs["e2e_ms"] = execs[phase_cols].sum(axis=1,
                                            skipna=True) * 1e3
    for app, grp in execs.groupby("app"):
        row = {"app": app, "queries": len(grp)}
        statuses = grp["status"].value_counts() \
            if "status" in grp.columns else {}
        for status, n in dict(statuses).items():
            row[f"n_{status}"] = int(n)
        for c in phase_cols:
            total = grp[c].sum(skipna=True)
            if total:
                row[c.replace("phase_", "total_", 1)] = round(
                    float(total), 4)
        q = grp["e2e_ms"].quantile
        row["p50_ms"] = round(float(q(0.50)), 3)
        row["p95_ms"] = round(float(q(0.95)), 3)
        row["p99_ms"] = round(float(q(0.99)), 3)
        rows.append(row)
    return pd.DataFrame(rows)

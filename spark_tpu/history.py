"""Event-log replay: the HistoryServer analog, sized to this engine.

The reference persists a typed event stream (`EventLoggingListener.scala`)
and rebuilds UI state by replay (`HistoryServer.scala:50` +
`ReplayListenerBus`). Here each query execution appends one JSON line
(plan fingerprint, phase timings, per-operator metrics) and replay is a
DataFrame over those lines — queryable with the engine itself or pandas.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

import pandas as pd


def read_event_log(log_dir: str, app: Optional[str] = None) -> pd.DataFrame:
    """All logged query executions as a flat DataFrame (one row per
    execution: ts, plan, per-phase seconds, metric columns)."""
    pattern = os.path.join(log_dir, f"app-{app or '*'}.jsonl")
    rows: List[dict] = []
    for path in sorted(glob.glob(pattern)):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                row = {"ts": e.get("ts"), "plan": e.get("plan"),
                       "app": os.path.basename(path)}
                for k, v in (e.get("phase_times_s") or {}).items():
                    row[f"phase_{k}_s"] = v
                for k, v in (e.get("metrics") or {}).items():
                    row[k] = v
                for k, v in (e.get("fault_summary") or {}).items():
                    # recovery counters flatten to fault_* columns; the
                    # per-event record list stays nested
                    row[f"fault_{k}"] = v
                rows.append(row)
    return pd.DataFrame(rows)


#: recovery-action counters an execution's fault_summary may carry
#: (executor._record_fault actions + the aggregate backoff total)
FAULT_ACTIONS = ("transient_retry", "stage_timeout", "oom_cache_evict",
                 "oom_spill_reroute", "mesh_fallback")


def fault_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-execution failure-recovery summary from a read_event_log
    frame: one row per execution that survived at least one fault, with
    the count of each recovery action (retries, cache evictions, spill
    reroutes, mesh fallbacks, stage timeouts), the total backoff slept,
    and the bounded per-fault event records — the observability surface
    of the degradation ladder (execution/failures.py)."""
    rows: List[dict] = []
    cols = [c for c in events.columns if c.startswith("fault_")]
    if not cols:
        return pd.DataFrame(rows)

    def present(v) -> bool:
        if isinstance(v, (list, dict)):
            return True  # nested event records (pd.isna chokes on lists)
        return not pd.isna(v)

    for _, r in events.iterrows():
        acted = {c: r.get(c) for c in cols if present(r.get(c))}
        if not any(c != "fault_events" for c in acted):
            continue
        row = {"ts": r.get("ts"), "app": r.get("app")}
        for a in FAULT_ACTIONS:
            v = acted.get(f"fault_{a}")
            row[a] = 0 if v is None else int(v)
        bk = acted.get("fault_retry_backoff_ms")
        row["retry_backoff_ms"] = 0.0 if bk is None else float(bk)
        row["events"] = acted.get("fault_events") or []
        rows.append(row)
    return pd.DataFrame(rows)


def runtime_filter_summary(events: pd.DataFrame) -> pd.DataFrame:
    """Per-(execution, filter) runtime-filter pruning summary from a
    read_event_log frame: tag, rows tested, rows pruned, pruning ratio
    and the trace-time build cost — the observability surface of the
    runtime-filter subsystem (rtf_* metrics emitted by
    RuntimeFilterExec)."""
    rows: List[dict] = []
    tested_cols = [c for c in events.columns
                   if c.startswith("rtf_tested_")]
    for _, r in events.iterrows():
        for c in tested_cols:
            tag = c[len("rtf_tested_"):]
            tested = r.get(c)
            if pd.isna(tested):
                continue
            pruned = r.get(f"rtf_pruned_{tag}")
            rows.append({
                "ts": r.get("ts"),
                "app": r.get("app"),
                "tag": tag,
                "tested": int(tested),
                "pruned": None if pd.isna(pruned) else int(pruned),
                # None (not 0.0) when the pruned metric is absent:
                # "unknown" must not read as "pruned nothing"
                "ratio": (float(pruned) / float(tested)
                          if not pd.isna(pruned) and tested else None),
                "build_ms": r.get(f"rtf_build_ms_{tag}"),
            })
    return pd.DataFrame(rows)

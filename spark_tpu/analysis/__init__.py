"""Static analysis: pre-compile plan/jaxpr analyzer + source lints.

Spark's blueprint front-loads correctness: Catalyst's analyzer
validates the plan before any execution and Tungsten's codegen fails
fast on unsupported shapes. This package is that seat for the XLA
engine, with two halves:

- **Pre-compile analyzer** (`plan_analyzer` + `jaxpr_analyzer`): after
  planning and before `_compile_stage`, walk the physical plan (and,
  gated, the abstractly-evaluated jaxpr) and emit typed `Finding`s —
  dtype-overflow hazards, host-sync loops, recompile churn, mesh
  replication, x64 truncation. Findings flow through the listener bus
  (`on_analysis`) into the event log, render in
  `explain(analysis=True)`, and are governed by
  `spark_tpu.sql.analysis.{enabled,strict,jaxpr}` — strict mode raises
  `AnalysisFindingError` pre-compile on error-severity findings.
- **Source-lint framework** (`lints/`): a registry of AST passes over
  the package tree (metric prefixes, conf-key registration, fault-site
  wiring, tracer-leak shapes), run by `scripts/lint.py --all` in CI —
  the classes of bug previous rounds found by hand, as static checks.
"""

from .findings import (AnalysisFindingError, CATEGORIES, FINDING_CODES,
                       Finding, errors_of)
from .jaxpr_analyzer import analyze_jaxpr, trace_stage
from .plan_analyzer import analyze_plan
from .plan_integrity import (PlanChangeTracer, PlanIntegrityError,
                             PlanIntegrityValidator)

__all__ = [
    "AnalysisFindingError", "CATEGORIES", "FINDING_CODES", "Finding",
    "PlanChangeTracer", "PlanIntegrityError", "PlanIntegrityValidator",
    "analyze_jaxpr", "analyze_plan", "errors_of", "trace_stage",
]

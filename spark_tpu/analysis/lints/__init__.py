"""Source-lint framework: a registry of AST passes over the package.

`scripts/metrics_lint.py` proved the shape — one static pass that turns
a hand-found bug class (unregistered traced-metric names) into a CI
failure. This package generalizes it: each *pass* is a small class with
a name, a file scope, and a `check(tree, relpath, ctx)` method over a
parsed `ast` module; `run_passes` walks the repository once, parses
each file once, and feeds every in-scope pass. `scripts/lint.py --all`
is the CLI (preflight stage 6); `tests/test_analysis.py` runs each pass
against both a seeded synthetic violation and the real tree.

Built-in passes (lints/passes.py):

- ``metric-prefix``: every `ctx.add_metric` name uses a registered
  METRIC_PREFIXES prefix (the original metrics_lint).
- ``conf-key``: every `spark_tpu.*` conf-key string literal read or
  written through a Conf method (or bound to a `*_KEY` constant) is
  `register()`ed in config.py — a typo'd key silently reads `None`.
- ``fault-site``: fault-injection sites are consistent three ways:
  every `faults.fire("<site>")` seam is declared in
  `testing.faults.KNOWN_SITES`, every declared site is actually wired,
  and every inject-rule string literal (`site:fault:nth`) in the tree
  names a known site — a typo'd rule would otherwise never fire.
- ``tracer-leak``: `hash()` of non-constants and truthiness coercion
  of device values in `execution/`/`parallel/` — the PR-1
  `_dict_value_hashes` bug class (hashing a tracer poisons dict
  lookups with trace-order-dependent identities).
- ``readme-metrics``: every registered METRIC_PREFIXES entry appears
  in the README metric-name reference table (the operator-facing half
  of the metric-prefix registration discipline).
- ``rule-registry``: every optimizer `Rule` subclass carries a unique
  `name`, is reachable from `default_optimizer()`, and declares
  `schema_preserving` explicitly — the plan-integrity verifier's
  rule contract (RL100).

Concurrency passes (analysis/concurrency/lint_passes.py):

- ``guarded-by``: every declared shared mutable attribute is written
  only under its GUARDED_BY-registered lock; every threading lock in
  the engine is registered with an acquisition-order rank; waivers
  for intentional benign races are explicit and reviewer-visible.
- ``lock-order``: the static lock-acquisition graph (nested `with` +
  resolvable call-graph edges) is acyclic and every edge ascends in
  registry rank — the canonical order `testing.lockwatch` asserts at
  runtime.

Adding a pass: subclass `LintPass`, decorate with `@register_lint`,
give it `name`, `doc`, optionally override `scope`, implement `check`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


@dataclass(frozen=True)
class LintViolation:
    path: str  # repo-relative
    line: int
    pass_name: str
    message: str
    #: stable machine-readable finding code (CI gates key on it); a
    #: pass without per-violation codes inherits its class-level code
    code: str = ""
    #: "error" fails the lint; "warn"/"info" are surfaced only (every
    #: built-in pass emits error — the tree gates at zero errors)
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] " \
               f"{self.message}"

    def to_dict(self) -> dict:
        """The --json shape: pass name, file:line, severity, code."""
        return {"pass": self.pass_name, "code": self.code,
                "severity": self.severity, "path": self.path,
                "line": self.line, "message": self.message}


class LintContext:
    """Shared, lazily-built lookup tables the passes consult."""

    def __init__(self, repo: str = REPO):
        self.repo = repo
        #: informational lines passes surface next to violations (the
        #: guarded-by waiver list, lock-order graph size) — printed by
        #: the CLI and carried in --json, never failing the lint
        self.notes: List[str] = []
        self._conf_keys: Optional[set] = None
        self._metric_prefixes: Optional[tuple] = None
        self._fault_sites: Optional[tuple] = None
        self._fault_classes: Optional[tuple] = None

    @property
    def conf_keys(self) -> set:
        if self._conf_keys is None:
            from ...config import registry
            self._conf_keys = set(registry())
        return self._conf_keys

    @property
    def metric_prefixes(self) -> tuple:
        if self._metric_prefixes is None:
            from ...observability.metrics import METRIC_PREFIXES
            self._metric_prefixes = METRIC_PREFIXES
        return self._metric_prefixes

    @property
    def fault_sites(self) -> tuple:
        if self._fault_sites is None:
            from ...testing.faults import KNOWN_SITES
            self._fault_sites = tuple(KNOWN_SITES)
        return self._fault_sites

    @property
    def fault_classes(self) -> tuple:
        if self._fault_classes is None:
            from ...testing.faults import FAULT_CLASSES
            self._fault_classes = tuple(FAULT_CLASSES)
        return self._fault_classes


class LintPass:
    """One static pass. `check` returns (line, message[, code
    [, severity]]) tuples for a single parsed file; `finish`
    (optional) returns whole-tree violations after every file was
    seen — as (relpath, line, message[, code[, severity]]) tuples.
    Omitted codes default to the pass's class-level `code`; omitted
    severity to "error" (only error-severity violations fail the
    lint)."""

    name: str = "?"
    doc: str = ""
    #: default machine-readable code for this pass's violations
    code: str = ""

    def scope(self, relpath: str) -> bool:
        """Whether the pass wants this repo-relative .py file."""
        return relpath.startswith("spark_tpu/")

    def check(self, tree: ast.Module, relpath: str,
              ctx: LintContext) -> List[Tuple[int, str]]:
        raise NotImplementedError

    def finish(self, ctx: LintContext) -> List[Tuple[str, int, str]]:
        return []


LINT_PASSES: Dict[str, type] = {}


def register_lint(cls: type) -> type:
    if cls.name in LINT_PASSES:
        raise ValueError(f"duplicate lint pass: {cls.name}")
    LINT_PASSES[cls.name] = cls
    return cls


def _iter_py_files(repo: str):
    roots = ("spark_tpu", "scripts", "tests")
    for fname in sorted(os.listdir(repo)):
        if fname.endswith(".py"):
            yield fname
    for top in roots:
        base = os.path.join(repo, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.relpath(os.path.join(dirpath, name),
                                          repo)


def run_passes(names: Optional[List[str]] = None,
               repo: str = REPO,
               collect_notes: Optional[List[str]] = None
               ) -> List[LintViolation]:
    """Run the selected passes (default: all) over the repository.
    Parses each file once; a file that fails to parse is itself a
    violation (the tree must stay importable). `collect_notes`
    receives the passes' informational lines (waiver lists etc.)."""
    # import for side effect: the built-in passes register on import
    from . import passes as _passes  # noqa: F401
    from ..concurrency import lint_passes as _cpasses  # noqa: F401
    selected = names or sorted(LINT_PASSES)
    unknown = [n for n in selected if n not in LINT_PASSES]
    if unknown:
        raise ValueError(f"unknown lint pass(es) {unknown}; "
                         f"known: {sorted(LINT_PASSES)}")
    ctx = LintContext(repo)
    instances = [LINT_PASSES[n]() for n in selected]
    out: List[LintViolation] = []

    def emit(p, relpath, item):
        line, msg = item[0], item[1]
        code = item[2] if len(item) > 2 else (p.code or p.name)
        severity = item[3] if len(item) > 3 else "error"
        out.append(LintViolation(relpath, line, p.name, msg,
                                 code=code, severity=severity))

    for relpath in _iter_py_files(repo):
        in_scope = [p for p in instances if p.scope(relpath)]
        if not in_scope:
            continue
        path = os.path.join(repo, relpath)
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            out.append(LintViolation(relpath, e.lineno or 1, "parse",
                                     f"syntax error: {e.msg}",
                                     code="PARSE"))
            continue
        for p in in_scope:
            for item in p.check(tree, relpath, ctx):
                emit(p, relpath, item)
    for p in instances:
        for item in p.finish(ctx):
            emit(p, item[0], item[1:])
    if collect_notes is not None:
        collect_notes.extend(ctx.notes)
    return sorted(out, key=lambda v: (v.path, v.line, v.pass_name))

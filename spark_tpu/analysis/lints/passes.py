"""Built-in lint passes (see package docstring for the catalog)."""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from . import LintContext, LintPass, register_lint

# ---------------------------------------------------------------------------
# metric-prefix (the original scripts/metrics_lint.py, framework-hosted)
# ---------------------------------------------------------------------------


def _metric_prefix_of(node: ast.expr):
    """(kind, literal-or-None) for an add_metric name argument."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "literal", node.value
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str) \
                and node.values[0].value:
            return "fstring", node.values[0].value
        return "fstring", None
    return "dynamic", None


@register_lint
class MetricPrefixPass(LintPass):
    """Every `ctx.add_metric(...)` name must use a registered prefix
    (observability.metrics.METRIC_PREFIXES): an unregistered traced
    metric would flow into the event log but silently miss every
    history summary column."""

    name = "metric-prefix"
    code = "MP100"
    doc = "add_metric names use registered METRIC_PREFIXES prefixes"

    def check(self, tree, relpath, ctx: LintContext
              ) -> List[Tuple[int, str]]:
        problems = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_metric"
                    and node.args):
                continue
            kind, text = _metric_prefix_of(node.args[0])
            if text is None:
                problems.append(
                    (node.lineno,
                     f"metric name not statically attributable "
                     f"({kind} argument)"))
            elif not text.startswith(ctx.metric_prefixes):
                problems.append(
                    (node.lineno,
                     f"unregistered metric prefix: {text!r}"))
        return problems


# ---------------------------------------------------------------------------
# conf-key
# ---------------------------------------------------------------------------

#: what a conf key looks like (dots, camelCase segments)
_KEY_RX = re.compile(r"^spark_tpu(\.[A-Za-z][A-Za-z0-9]*)+$")

#: Conf methods whose first argument is a key
_CONF_METHODS = ("get", "set", "contains", "unset", "is_explicitly_set")


@register_lint
class ConfKeyPass(LintPass):
    """Every `spark_tpu.*` key string read/written through a Conf
    method — or bound to a `*_KEY` module constant — must be
    `register()`ed in config.py. A typo'd key never errors: `get`
    silently returns the fallback and the feature quietly disables
    (the PR-2 `stage_rnu` shape, for configuration)."""

    name = "conf-key"
    code = "CK100"
    doc = "conf-key string literals are registered in config.py"

    def scope(self, relpath: str) -> bool:
        if relpath == "spark_tpu/config.py":
            return False  # register() calls DEFINE the keys
        return (relpath.startswith(("spark_tpu/", "tests/", "scripts/"))
                or relpath == "bench.py")

    def check(self, tree, relpath, ctx: LintContext
              ) -> List[Tuple[int, str]]:
        problems = []

        def check_key(lineno: int, key: str, via: str) -> None:
            if key not in ctx.conf_keys:
                problems.append(
                    (lineno,
                     f"unregistered conf key {key!r} ({via}); add a "
                     f"register(...) entry in spark_tpu/config.py"))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CONF_METHODS and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str) \
                        and a.value.startswith("spark_tpu."):
                    check_key(a.lineno, a.value,
                              f"conf.{node.func.attr}")
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and _KEY_RX.match(node.value.value):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if any(n.endswith("_KEY") for n in names):
                    check_key(node.lineno, node.value.value,
                              f"{names[0]} constant")
        return problems


# ---------------------------------------------------------------------------
# fault-site
# ---------------------------------------------------------------------------

FAULTS_MODULE = "spark_tpu/testing/faults.py"


@register_lint
class FaultSitePass(LintPass):
    """Three-way consistency for fault-injection sites: `faults.fire`
    seams <-> `testing.faults.KNOWN_SITES` <-> inject-rule string
    literals in tests/scripts. A rule naming an unwired site would arm
    and then never fire — the chaos test silently tests nothing."""

    name = "fault-site"
    code = "FS100"
    doc = "fault sites are declared, wired, and spelled consistently"

    def __init__(self):
        self._engine_wired: dict = {}  # site -> first (relpath, line)
        self._registered: set = set()  # register_site("...") literals
        self._uses: list = []  # (relpath, line, site, via)

    def scope(self, relpath: str) -> bool:
        return (relpath.startswith(("spark_tpu/", "tests/", "scripts/"))
                or relpath == "bench.py")

    def _spec_rules(self, text: str, ctx: LintContext):
        """Parse `text` as an inject spec; None unless EVERY comma part
        matches `site:fault:nth[:arg]` with a known fault class (the
        disambiguator against arbitrary colon-bearing strings)."""
        rules = []
        parts = [p for p in text.split(",") if p.strip()]
        if not parts:
            return None
        for part in parts:
            bits = part.strip().split(":")
            if len(bits) not in (3, 4) or any(" " in b for b in bits):
                return None
            if not re.match(r"^[a-z_][a-z0-9_]*$", bits[0]):
                return None  # f-string fragments etc. — not a spec
            if bits[1] not in ctx.fault_classes:
                return None
            if not bits[2].isdigit():
                return None
            rules.append(bits[0])
        return rules

    def check(self, tree, relpath, ctx: LintContext
              ) -> List[Tuple[int, str]]:
        # collect only; every verdict lands in finish(), so the pass is
        # independent of file-walk order (a test may register_site a
        # seam the same file then uses)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args \
                    and relpath != FAULTS_MODULE:
                fn = node.func
                callee = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                a = node.args[0]
                lit = a.value if (isinstance(a, ast.Constant)
                                  and isinstance(a.value, str)) else None
                if callee == "fire" and lit is not None:
                    if relpath.startswith("spark_tpu/"):
                        self._engine_wired.setdefault(
                            lit, (relpath, a.lineno))
                    self._uses.append((relpath, a.lineno, lit, "fire"))
                elif callee in ("register_site", "scoped_site") \
                        and lit is not None:
                    self._registered.add(lit)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                for site in self._spec_rules(node.value, ctx) or ():
                    self._uses.append((relpath, node.lineno, site,
                                       "inject rule"))
        return []

    def finish(self, ctx: LintContext):
        known = set(ctx.fault_sites) | self._registered
        out = []
        seen = set()
        for relpath, line, site, via in self._uses:
            if site in known or (relpath, line, site) in seen:
                continue
            seen.add((relpath, line, site))
            out.append((relpath, line,
                        f"{via} names unknown fault site {site!r} "
                        f"(not in KNOWN_SITES, never register_site'd); "
                        f"known: {ctx.fault_sites}"))
        for site in ctx.fault_sites:
            if site not in self._engine_wired:
                out.append((FAULTS_MODULE, 1,
                            f"KNOWN_SITES declares {site!r} but no "
                            f"faults.fire({site!r}) seam wires it"))
        return out


# ---------------------------------------------------------------------------
# readme-metrics
# ---------------------------------------------------------------------------


@register_lint
class ReadmeMetricsPass(LintPass):
    """Every registered METRIC_PREFIXES entry must appear in the README
    metric-name reference table: a prefix the docs don't list is a
    metric family operators can't discover (the README table is the
    operator-facing half of the registration discipline the
    metric-prefix pass enforces in code)."""

    name = "readme-metrics"
    code = "RM100"
    doc = "every METRIC_PREFIXES entry appears in the README table"

    def scope(self, relpath: str) -> bool:
        return False  # whole-tree pass: finish() reads README.md

    def check(self, tree, relpath, ctx: LintContext):
        return []

    def finish(self, ctx: LintContext):
        import os
        path = os.path.join(ctx.repo, "README.md")
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            return [("README.md", 1, "README.md unreadable")]
        out = []
        for prefix in ctx.metric_prefixes:
            if f"`{prefix}" not in text:
                out.append(
                    ("README.md", 1,
                     f"metric prefix `{prefix}` (METRIC_PREFIXES) is "
                     f"missing from the README metric-name reference "
                     f"table"))
        return out


# ---------------------------------------------------------------------------
# rule-registry
# ---------------------------------------------------------------------------


@register_lint
class RuleRegistryPass(LintPass):
    """Optimizer-rule registration discipline, enforced at the class
    level: every `Rule` subclass in the engine (1) carries a unique
    `name` (rule traces, `excludedRules` ablation and
    `PlanIntegrityError` attribution all key on it), (2) is reachable
    from `default_optimizer()` (an orphaned rule is dead code the
    fuzzer can never ablate), and (3) declares `schema_preserving`
    explicitly in its own body — the plan-integrity verifier holds
    undeclared rules to the preservation contract, so an implicit
    inheritance is a latent false positive/negative."""

    name = "rule-registry"
    code = "RL100"
    doc = "Rule subclasses: unique name, reachable, explicit " \
          "schema_preserving"

    def scope(self, relpath: str) -> bool:
        return False  # whole-tree pass: finish() imports the registry

    def check(self, tree, relpath, ctx: LintContext):
        return []

    def _subclasses(self, base) -> list:
        out = []
        for cls in base.__subclasses__():
            out.append(cls)
            out.extend(self._subclasses(cls))
        return out

    def finish(self, ctx: LintContext):
        import inspect
        import os
        from ...plan import join_reorder  # noqa: F401 — registers rules
        from ...plan import optimizer
        from ...plan.rules import Rule

        def site(cls) -> Tuple[str, int]:
            try:
                relpath = os.path.relpath(inspect.getsourcefile(cls),
                                          ctx.repo)
                line = inspect.getsourcelines(cls)[1]
            except (OSError, TypeError):
                relpath, line = "spark_tpu/plan/rules.py", 1
            return relpath, line

        engine_rules = [cls for cls in self._subclasses(Rule)
                        if cls.__module__.startswith("spark_tpu.")]
        reachable = {type(r)
                     for b in optimizer.default_optimizer().batches
                     for r in b.rules}
        out = []
        by_name: dict = {}
        for cls in engine_rules:
            relpath, line = site(cls)
            rname = cls.__dict__.get("name")
            if not rname:
                out.append((relpath, line,
                            f"Rule subclass {cls.__name__} has no "
                            f"`name` of its own (traces/ablation/"
                            f"integrity errors key on it)"))
            elif rname in by_name:
                out.append((relpath, line,
                            f"duplicate rule name {rname!r} (also "
                            f"{by_name[rname].__name__}): excludedRules "
                            f"and rule traces cannot distinguish them"))
            else:
                by_name[rname] = cls
            if cls not in reachable:
                out.append((relpath, line,
                            f"rule {cls.__name__} is not reachable "
                            f"from default_optimizer(): dead rule the "
                            f"fuzzer can never ablate"))
            if not isinstance(cls.__dict__.get("schema_preserving"),
                              bool):
                out.append((relpath, line,
                            f"rule {cls.__name__} does not declare "
                            f"`schema_preserving` in its own body; the "
                            f"plan-integrity verifier needs the "
                            f"explicit contract (True = must preserve "
                            f"the root schema, False = legitimately "
                            f"reshapes)"))
        ctx.notes.append(
            f"rule-registry: {len(engine_rules)} engine rule(s), "
            f"{len(reachable)} reachable from default_optimizer")
        return out


# ---------------------------------------------------------------------------
# tracer-leak
# ---------------------------------------------------------------------------

#: names/attributes whose presence marks an expression as (potentially)
#: traced device data
_TRACED_NAMES = ("jnp", "lax")
_TRACED_ATTRS = ("data", "validity", "elem_validity", "selection")


def _mentions_traced(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _TRACED_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _TRACED_ATTRS:
            return True
    return False


@register_lint
class TracerLeakPass(LintPass):
    """The PR-1 `_dict_value_hashes` bug class: `hash()` of a traced
    value (or truthiness coercion of device data) inside the trace-time
    modules produces trace-order-dependent identities — dict/set keying
    on them silently misbehaves across retraces. Flag the shapes
    statically in the trace-adjacent packages: execution/ + parallel/
    (the original scope), plus service/, streaming.py and
    observability/ — all of which hold device values since the
    PR-6/8/11 concurrency work (the scope predates them)."""

    name = "tracer-leak"
    code = "TL100"
    doc = "no hash()/bool() of traced values in trace-time modules"

    def scope(self, relpath: str) -> bool:
        return relpath.startswith(("spark_tpu/execution/",
                                   "spark_tpu/parallel/",
                                   "spark_tpu/service/",
                                   "spark_tpu/observability/")) \
            or relpath == "spark_tpu/streaming.py"

    def check(self, tree, relpath, ctx: LintContext
              ) -> List[Tuple[int, str]]:
        problems = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            if node.func.id == "hash" and node.args:
                if not all(isinstance(a, ast.Constant)
                           for a in node.args):
                    problems.append(
                        (node.lineno,
                         "hash() of a non-constant in a trace-time "
                         "module: a traced value here yields a "
                         "trace-order-dependent identity (use a "
                         "structural key instead)"))
            elif node.func.id == "bool" and node.args \
                    and _mentions_traced(node.args[0]):
                problems.append(
                    (node.lineno,
                     "bool() over device data in a trace-time module: "
                     "coercing a tracer raises (or silently "
                     "host-syncs a concrete array)"))
        return problems

"""Rule-granular plan-integrity verification + plan-change tracing.

The reference guards its optimizer seam with structural-integrity
validation (`spark.sql.planChangeValidation`, `LogicalPlanIntegrity`)
and `PlanChangeLogger` inside `catalyst/rules/RuleExecutor.scala`; this
module is that seat for the engine's `RuleExecutor`. After every
EFFECTIVE rule application (`spark_tpu.sql.planChangeValidation` =
``lite`` | ``full``) it checks:

- **resolution**: every `ColumnRef` in every expression slot resolves
  against its node's child schema(s) with a UNIQUE origin (ambiguous or
  dangling references are how a rewrite silently drops/duplicates rows);
- **schema preservation**: the ROOT output schema (names, dtypes,
  nullability) is unchanged across the rule unless the rule declares
  itself schema-changing via the `Rule.schema_preserving = False`
  contract (PruneColumns, RewriteGroupKeyAggregates, ... declare;
  everything else must preserve);
- **structure**: no duplicate output names at any node, Aggregate nodes
  stay coherent (at least one group or aggregate expression), and join
  key pairs keep coercible dtypes;
- **determinism**: re-running the batch on a structurally cloned input
  yields a tree-string-identical plan, so stage keys (and the
  persistent compile cache keyed off them) can't be poisoned by a
  nondeterministic rewrite.

Violations raise a typed `PlanIntegrityError` naming the rule, batch
and first offending node in ``full`` mode; in ``lite`` they surface as
`PLAN_INTEGRITY` findings through the `analysis/findings.py` flow
(listener bus -> event log -> `explain(analysis=True)`).

`PlanChangeTracer` is the `PlanChangeLogger` analog: one record per
(batch, rule) in first-application order — invocations, effective
count, total ms and (under `spark_tpu.sql.planChangeLog`) a unified
before/after tree diff of the first effective application. The records
ride the schema-v7 `rule_trace` event-log field, `explain(rules=True)`
and `GET /queries/<id>/plan`.
"""

from __future__ import annotations

import copy
import difflib
from typing import Dict, List, Optional, Tuple

from .. import types as T
from ..expr import Alias, ColumnRef, Expression, case_sensitive
from ..plan import logical as L
from .findings import Finding

VALIDATION_KEY = "spark_tpu.sql.planChangeValidation"
CHANGE_LOG_KEY = "spark_tpu.sql.planChangeLog"

#: cap on stored diff text so a pathological plan can't bloat the
#: event log (the tracer keeps the head of the first effective diff)
MAX_DIFF_LINES = 60


class PlanIntegrityError(RuntimeError):
    """A rule application broke a plan invariant. Names the rule, the
    batch and the first offending node so the failing rewrite is
    attributable without bisecting the optimizer."""

    def __init__(self, batch: str, rule: str, check: str,
                 node: str, message: str):
        self.batch = batch
        self.rule = rule
        self.check = check
        self.node = node
        super().__init__(
            f"plan integrity violated by rule {rule!r} (batch {batch!r},"
            f" check {check}) at node {node}: {message}")


# ---------------------------------------------------------------------------
# Structural checks (resolution / duplicates / coherence / join dtypes)
# ---------------------------------------------------------------------------


def _node_expr_slots(node: L.LogicalPlan
                     ) -> List[Tuple[Expression, T.Schema]]:
    """(expression, resolution schema) pairs for one node — the node-
    local view of `logical.iter_expressions` (which flattens the whole
    tree and would lose WHICH child schema each slot resolves against)."""
    out: List[Tuple[Expression, T.Schema]] = []
    if isinstance(node, L.Project):
        cs = node.child.schema()
        out += [(e, cs) for e in node.exprs]
    elif isinstance(node, L.Filter):
        out.append((node.condition, node.child.schema()))
    elif isinstance(node, L.Join):
        ls, rs = node.left.schema(), node.right.schema()
        out += [(k, ls) for k in node.left_keys]
        out += [(k, rs) for k in node.right_keys]
        if node.condition is not None:
            # residual predicates see the post-rename combined row
            # (left fields + `_r`-suffixed right fields), even for
            # semi/anti joins whose OUTPUT schema is left-only
            nm = node.right_name_map()
            fields = list(ls.fields) + [
                T.Field(nm[f.name], f.dtype, f.nullable)
                for f in rs.fields]
            out.append((node.condition, T.Schema(fields)))
    elif isinstance(node, L.Aggregate):
        cs = node.child.schema()
        out += [(g, cs) for g in node.group_exprs]
        for a in node.agg_exprs:
            out += [(c, cs) for c in a.func.children]
    elif isinstance(node, L.Sort):
        cs = node.child.schema()
        out += [(o.child, cs) for o in node.orders]
    elif isinstance(node, L.WindowPlan):
        cs = node.child.schema()
        for w, _name in node.wexprs:
            out += [(c, cs) for c in w.children]
    elif isinstance(node, L.Generate):
        out.append((node.gen_expr, node.child.schema()))
    return out


def _iter_refs(e: Expression):
    if isinstance(e, ColumnRef):
        yield e
    for c in e.children:
        yield from _iter_refs(c)


def _origin_count(schema: T.Schema, name: str) -> int:
    """How many schema fields the engine's resolution rules would match
    for `name` (mirrors expr._resolve_field: exact first, then the
    case-insensitive fallback)."""
    exact = sum(1 for f in schema.fields if f.name == name)
    if exact or case_sensitive():
        return exact
    low = name.lower()
    return sum(1 for f in schema.fields if f.name.lower() == low)


def check_plan(plan: L.LogicalPlan) -> List[dict]:
    """Walk one plan and return every structural-invariant violation as
    `{"check", "node", "message"}` dicts (empty = clean). Schema
    computation failures anywhere surface as `resolution` violations
    rather than escaping as raw AnalysisError."""
    violations: List[dict] = []
    stack = [plan]
    nodes: List[L.LogicalPlan] = []
    while stack:
        n = stack.pop()
        nodes.append(n)
        stack.extend(n.children)
    for node in nodes:
        label = node.simple_string()[:160]
        # -- output schema computes, with unique output names ----------
        try:
            schema = node.schema()
        except Exception as e:  # noqa: BLE001 — any failure is the finding
            violations.append({
                "check": "resolution", "node": label,
                "message": f"schema computation failed: {e}"})
            continue
        names = schema.names
        dupes = sorted({n_ for n_ in names if names.count(n_) > 1})
        if dupes:
            violations.append({
                "check": "duplicate-names", "node": label,
                "message": f"duplicate output column(s) {dupes}"})
        # -- every ColumnRef resolves with a unique origin -------------
        try:
            slots = _node_expr_slots(node)
        except Exception as e:  # noqa: BLE001
            violations.append({
                "check": "resolution", "node": label,
                "message": f"child schema computation failed: {e}"})
            continue
        for expr, res_schema in slots:
            for ref in _iter_refs(expr):
                cnt = _origin_count(res_schema, ref.name())
                if cnt == 1:
                    continue
                what = "unresolvable" if cnt == 0 else \
                    f"ambiguous ({cnt} origins)"
                violations.append({
                    "check": "resolution", "node": label,
                    "message": f"column {ref.name()!r} is {what} "
                               f"against {res_schema.names}"})
        # -- node-specific coherence -----------------------------------
        if isinstance(node, L.Aggregate):
            if not node.group_exprs and not node.agg_exprs:
                violations.append({
                    "check": "aggregate-coherence", "node": label,
                    "message": "Aggregate with neither group nor "
                               "aggregate expressions"})
        if isinstance(node, L.Join):
            try:
                ls, rs = node.left.schema(), node.right.schema()
                for lk, rk in zip(node.left_keys, node.right_keys):
                    lt, rt = lk.dtype(ls), rk.dtype(rs)
                    try:
                        T.common_type(lt, rt)
                    except TypeError:
                        violations.append({
                            "check": "join-key-dtype", "node": label,
                            "message": f"join key pair {lk!r} ({lt!r}) "
                                       f"= {rk!r} ({rt!r}) has no "
                                       f"common type"})
            except Exception:  # noqa: BLE001 — resolution already reported
                pass
    return violations


def schema_delta(before: T.Schema, after: T.Schema) -> Optional[str]:
    """None when the two output schemas agree on names, dtypes and
    nullability; otherwise a one-line description of the first drift."""
    if len(before.fields) != len(after.fields):
        return (f"column count {len(before.fields)} -> "
                f"{len(after.fields)} ({before.names} -> {after.names})")
    for i, (a, b) in enumerate(zip(before.fields, after.fields)):
        if a.name != b.name:
            return f"column {i} renamed {a.name!r} -> {b.name!r}"
        if a.dtype != b.dtype:
            return f"column {a.name!r} dtype {a.dtype!r} -> {b.dtype!r}"
        if a.nullable != b.nullable:
            return (f"column {a.name!r} nullability "
                    f"{a.nullable} -> {b.nullable}")
    return None


def clone_plan(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Node-level structural clone (leaf sources and expressions stay
    shared): enough to catch a rule that depends on node identity or
    mutates nodes in place, without deep-copying table data."""
    new = copy.copy(plan)
    new.children = tuple(clone_plan(c) for c in plan.children)
    return new


# ---------------------------------------------------------------------------
# The validator (RuleExecutor hook)
# ---------------------------------------------------------------------------


class PlanIntegrityValidator:
    """`mode` = ``lite`` (collect `PLAN_INTEGRITY` findings) or ``full``
    (raise `PlanIntegrityError` at the first violation). Installed into
    `RuleExecutor` by `QueryExecution.optimized_plan` when
    `spark_tpu.sql.planChangeValidation` != off."""

    def __init__(self, mode: str = "full"):
        if mode not in ("lite", "full"):
            raise ValueError(f"invalid validation mode {mode!r}")
        self.mode = mode
        self.findings: List[Finding] = []
        #: (plan object, its violation set) from the last after_rule —
        #: rules run sequentially, so the previous rule's `after` IS
        #: the next rule's `before` (by identity) and its check_plan
        #: walk can be reused as the baseline
        self._last_checked = None

    def _report(self, batch: str, rule: str, check: str, node: str,
                message: str) -> None:
        if self.mode == "full":
            raise PlanIntegrityError(batch, rule, check, node, message)
        self.findings.append(Finding(
            code="PLAN_INTEGRITY",
            message=f"rule {rule!r} (batch {batch!r}, check {check}) "
                    f"at {node}: {message}",
            op=rule,
            detail={"batch": batch, "rule": rule, "check": check,
                    "node": node}))

    def after_rule(self, batch: str, rule, before: L.LogicalPlan,
                   after: L.LogicalPlan) -> None:
        """Invariants on one EFFECTIVE rule application. Violations
        already present in `before` are NOT attributed to the rule —
        a user plan may legally carry e.g. duplicate output names
        (`SELECT k, k`), and only NEW breakage is the rule's fault."""
        cached = self._last_checked
        if cached is not None and cached[0] is before:
            baseline = cached[1]
        else:
            baseline = {(v["check"], v["message"])
                        for v in check_plan(before)}
        after_violations = check_plan(after)
        self._last_checked = (after, {(v["check"], v["message"])
                                      for v in after_violations})
        for v in after_violations:
            if (v["check"], v["message"]) in baseline:
                continue
            self._report(batch, rule.name, v["check"], v["node"],
                         v["message"])
        preserving = getattr(rule, "schema_preserving", None)
        if preserving is not False:
            # undeclared rules are held to the preservation contract
            # (RL100 separately forces the declaration to be explicit)
            try:
                delta = schema_delta(before.schema(), after.schema())
            except Exception:  # noqa: BLE001 — reported by check_plan
                delta = None
            if delta is not None:
                self._report(batch, rule.name, "schema-preservation",
                             after.simple_string()[:160], delta)

    def after_batch(self, batch, batch_input: L.LogicalPlan,
                    batch_output: L.LogicalPlan, rerun) -> None:
        """Determinism: `rerun(plan)` (a side-effect-free replay of the
        batch, provided by the executor) over a structural clone of the
        batch input must reproduce the batch output exactly."""
        try:
            replay = rerun(clone_plan(batch_input))
        except Exception as e:  # noqa: BLE001 — a replay-only failure
            self._report(batch.name, "*", "determinism",
                         batch_input.simple_string()[:160],
                         f"batch replay raised: {e}")
            return
        if replay.tree_string() != batch_output.tree_string():
            diff = "\n".join(difflib.unified_diff(
                batch_output.tree_string().splitlines(),
                replay.tree_string().splitlines(),
                "first run", "replay", lineterm=""))[:2000]
            self._report(batch.name, "*", "determinism",
                         batch_output.simple_string()[:160],
                         "replaying the batch produced a different "
                         "plan:\n" + diff)


# ---------------------------------------------------------------------------
# Plan-change tracing (PlanChangeLogger analog)
# ---------------------------------------------------------------------------


class PlanChangeTracer:
    """Per-(batch, rule) application records in first-application order:
    `{"batch", "rule", "invocations", "effective", "ms"[, "diff"]}` —
    the event-log `rule_trace` payload. `diffs=True` (conf
    `spark_tpu.sql.planChangeLog`) captures a unified before/after tree
    diff of each rule's FIRST effective application."""

    def __init__(self, diffs: bool = False):
        self.diffs = diffs
        self.records: List[Dict] = []
        self._index: Dict[Tuple[str, str], Dict] = {}

    def after_rule(self, batch: str, rule, before: L.LogicalPlan,
                   after: L.LogicalPlan, effective: bool,
                   ms: float) -> None:
        key = (batch, rule.name)
        rec = self._index.get(key)
        if rec is None:
            rec = {"batch": batch, "rule": rule.name,
                   "invocations": 0, "effective": 0, "ms": 0.0}
            self._index[key] = rec
            self.records.append(rec)
        rec["invocations"] += 1
        rec["ms"] = round(rec["ms"] + ms, 3)
        if effective:
            rec["effective"] += 1
            if self.diffs and "diff" not in rec:
                lines = list(difflib.unified_diff(
                    before.tree_string().splitlines(),
                    after.tree_string().splitlines(),
                    "before", "after", lineterm=""))[:MAX_DIFF_LINES]
                rec["diff"] = "\n".join(lines)

    def render(self) -> List[str]:
        """explain(rules=True) lines."""
        if not self.records:
            return ["  no rules applied"]
        return render_trace(self.records)


def render_trace(records: List[Dict]) -> List[str]:
    """Human-readable lines for a rule_trace record list (shared by
    explain(rules=True) and any log replay tooling)."""
    out = []
    for r in records:
        out.append(f"  {r['batch']}.{r['rule']}: "
                   f"effective {r['effective']}/{r['invocations']}, "
                   f"{r['ms']}ms")
        for line in (r.get("diff") or "").splitlines():
            out.append("    " + line)
    return out

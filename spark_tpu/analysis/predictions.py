"""Plan-time size predictions, graded against observed metrics.

The ROADMAP self-grading lever: the planner and analyzer predict sizes
everywhere — exchange routed bytes from row estimates, join output
capacities, aggregate group counts — but until now nothing ever
checked those predictions against what the metrics channel measured,
so a systematically-wrong estimator (the thing that mis-seeds AQE
capacities and mis-sizes runtime filters) was invisible.

`predict_plan` walks the planned physical tree (pure host work,
microseconds — cheaper than the analyzer walk that already runs per
query) and emits one record per predictable site:

    {"kind": "exch_rows"|"exch_bytes"|"join_rows"|"agg_groups",
     "tag": <node tag>, "predicted": <int>, "basis": <how derived>}

The executor attaches the list to the event-log record
(`predictions`, schema v3); `history.grade_predictions` joins each
record against the observed metric of the same tag
(`exch_bytes_<tag>`, `join_rows_<tag>`, `agg_groups_<tag>`) and grades
it hit / over / under; `history.prediction_report` runs that over a
replayed event log, and bench.py emits the per-query mean |error| as
the `tpch_*_pred_err_pct` sidecar. Event-log `analysis_findings`
carrying byte bounds (mesh replication, hash-table pressure, spill
estimates) are graded by the same report against observed exchange
bytes and stage peak-HBM.
"""

from __future__ import annotations

from typing import List, Optional

from ..plan import physical as P


def _estimate_rows(node: P.PhysicalPlan) -> Optional[int]:
    from ..plan.runtime_filter import estimate_rows_physical
    try:
        return estimate_rows_physical(node)
    except Exception:  # noqa: BLE001 — estimates are best-effort
        return None


def _row_width(node: P.PhysicalPlan) -> int:
    try:
        return 8 * max(1, len(node.schema().fields))
    except Exception:  # noqa: BLE001
        return 8


def predict_plan(root: P.PhysicalPlan, conf, mesh_n: int = 1
                 ) -> List[dict]:
    """One prediction record per exchange / join / aggregate in the
    planned tree. Pure host-side walk; never raises past a node."""
    out: List[dict] = []
    seen = set()  # runtime-filter creation chains DAG-share nodes

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        try:
            _predict_node(node, out, mesh_n)
        except Exception:  # noqa: BLE001 — advisory only
            pass

    walk(root)
    try:
        _predict_udf(root, conf, out)
    except Exception:  # noqa: BLE001 — advisory only
        pass
    return out


def _predict_udf(root: P.PhysicalPlan, conf, out: List[dict]) -> None:
    """Predicted Arrow batch/row traffic through the UDF worker lane,
    graded against the observed `udf_batches`/`udf_rows` counters.
    Worker mode only: the in-process lane evaluates whole batches and
    never slices, so the batch count is not a prediction there."""
    if str(conf.get("spark_tpu.sql.udf.mode") or "inprocess") != "worker":
        return
    from ..execution.python_eval import node_udfs
    max_rec = int(conf.get(
        "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"))
    rows_total = 0
    seen = set()

    def walk(node):
        nonlocal rows_total
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if not node_udfs(node):
            return
        rows = _estimate_rows(node.children[0] if node.children
                              else node)
        if rows is not None and rows > 0:
            rows_total += rows

    walk(root)
    if rows_total <= 0:
        return
    out.append({"kind": "udf_rows", "tag": "udf",
                "predicted": int(rows_total), "basis": "scan-estimate"})
    out.append({"kind": "udf_batches", "tag": "udf",
                "predicted": int(-(-rows_total // max_rec)),
                "basis": f"rows/{max_rec}"})


def _predict_node(node, out: List[dict], mesh_n: int) -> None:
    if isinstance(node, P.ExchangeExec):
        if mesh_n <= 1:
            return  # identity on a single chip: nothing observable
        rows = _estimate_rows(node.children[0])
        if rows is None or rows <= 0:
            return
        width = _row_width(node.children[0])
        out.append({"kind": "exch_rows", "tag": node.tag,
                    "predicted": int(rows), "basis": "scan-estimate"})
        out.append({"kind": "exch_bytes", "tag": node.tag,
                    "predicted": int(rows) * width,
                    "basis": f"rows*{width}B"})
    elif isinstance(node, P.JoinExec):
        if node.out_cap is not None:
            # a seeded/learned capacity is itself a prediction of the
            # true output-row total — grade how tight the AQE seat is
            out.append({"kind": "join_rows", "tag": node.tag,
                        "predicted": int(node.out_cap),
                        "basis": "out_cap"})
        elif getattr(node, "cbo_est_rows", None) is not None:
            # the reorder cost model's own output estimate — grading it
            # against join_rows_<tag> closes the loop on the order
            # decisions (a systematically-wrong model shows up in
            # history.prediction_report as basis cbo-reorder misses)
            out.append({"kind": "join_rows", "tag": node.tag,
                        "predicted": int(node.cbo_est_rows),
                        "basis": "cbo-reorder"})
        else:
            rows = _estimate_rows(node.children[0])
            if rows is not None and rows > 0:
                out.append({"kind": "join_rows", "tag": node.tag,
                            "predicted": int(rows),
                            "basis": "probe-estimate"})
    elif isinstance(node, P.HashAggregateExec):
        if node.est_groups:
            out.append({"kind": "agg_groups", "tag": node.tag,
                        "predicted": int(node.est_groups),
                        "basis": "est_groups"})

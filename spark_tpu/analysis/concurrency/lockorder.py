"""Static lock-acquisition graph: extract, then prove it deadlock-free.

Edges come from three sources:

1. lexically nested ``with`` blocks on registered locks (``with
   self._cv: ... CACHE.evict_bytes(...)``);
2. the call graph, where a call made while holding a lock resolves —
   through the registry's receiver tables — to methods whose own
   (transitive) acquisitions are known. Resolution is deliberately
   conservative: only ``self``, the named receivers/attrs in the
   registry, factory-return chains (``self.metrics.counter(x).inc()``)
   and unique module-level functions resolve; anything else
   contributes no edge (lockwatch observes the real runtime edges);
3. ``EXTRA_EDGES``: declared, commented edges for holds the lexical
   extractor cannot see (the session lease held across ``submit``,
   opaque callbacks like admission's ``on_event``).

Verdicts: an edge ``a -> b`` must STRICTLY ASCEND in registry rank
(LO202) — with every edge ascending the graph is acyclic, the ranking
is the canonical acquisition order, and lockwatch asserts runtime
edges against the same ranks. Cycle detection (LO201) still runs
independently, so a registry with duplicated ranks cannot hide a
cycle, and acquiring a non-reentrant lock while already holding it is
a self-deadlock (LO201).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .guarded import RegistryView, _dotted

CODE_CYCLE = "LO201"
CODE_RANK = "LO202"

#: a resolved callable: (class name, method) — class "" = module fn
_Fn = Tuple[str, str]


class LockOrderAnalysis:
    """Feed files with `add_file`, then `finish()` -> (edges,
    violations). Edges map (lock_a, lock_b) -> human 'where' string."""

    def __init__(self, view: Optional[RegistryView] = None):
        self.view = view or RegistryView()
        #: fn -> list of (held lock ids, ("acquire", lock) | ("call", fn))
        self._events: Dict[_Fn, List[Tuple[Tuple[str, ...], str,
                                           object]]] = {}
        #: fn -> source location of its def
        self._where: Dict[_Fn, str] = {}
        #: module-level function name -> fn key (None = ambiguous)
        self._module_fns: Dict[str, Optional[_Fn]] = {}
        self._lock_attr_ids: Dict[Tuple[str, str], str] = {}

    # -- extraction ---------------------------------------------------------

    def add_file(self, relpath: str, tree: ast.Module) -> None:
        if relpath not in self.view.scanned_relpaths():
            return
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(relpath, node.name, meth)
                    elif isinstance(meth, ast.ClassDef):
                        # one level of nesting (_Slot in admission)
                        for sub in meth.body:
                            if isinstance(sub, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)):
                                self._scan_fn(relpath, meth.name, sub)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._scan_fn(relpath, "", node)
                key = (f"mod:{relpath}", node.name)
                if node.name in self._module_fns \
                        and self._module_fns[node.name] != key:
                    # same function name in two scanned files: refuse
                    # to link it (a wrong charge would fabricate or
                    # mask edges with the wrong 'where')
                    self._module_fns[node.name] = None
                else:
                    self._module_fns[node.name] = key

    def _scan_fn(self, relpath: str, cls: str, fn) -> None:
        # module functions are keyed per-FILE (a "mod:<relpath>"
        # pseudo-class): two scanned files defining the same function
        # name must not merge their event lists — name-based linking
        # happens in _link, which refuses ambiguous names. Class
        # methods stay name-keyed: the receiver tables resolve by
        # class NAME by contract, and scanned class names are unique.
        key = (cls, fn.name) if cls else (f"mod:{relpath}", fn.name)
        self._where.setdefault(key, f"{relpath}:{fn.lineno}")
        events = self._events.setdefault(key, [])
        held0: Tuple[str, ...] = ()
        held_attr = self.view.held_callees.get((relpath, cls, fn.name))
        if held_attr is not None:
            lid = self.view.class_locks(relpath, cls).get(held_attr)
            if lid is not None:
                held0 = (lid,)
        self._walk(fn.body, relpath, cls, held0, events)

    def _resolve_lock(self, expr, relpath: str, cls: str
                      ) -> Optional[str]:
        """A with-item / acquire target -> lock id, when resolvable."""
        d = _dotted(expr)
        if d is not None:
            parts = d.split(".")
            if parts[0] == "self" and len(parts) == 2:
                return self.view.class_locks(relpath, cls).get(parts[1])
            if len(parts) == 1:
                return self.view.class_locks(relpath, "").get(parts[0])
            if len(parts) == 2 \
                    and parts[0] in self.view.receiver_names:
                rcls = self.view.receiver_names[parts[0]]
                for decl in self.view.locks:
                    if decl.cls == rcls and decl.attr == parts[1]:
                        return decl.lock_id
            return None
        if isinstance(expr, ast.Call):
            target = self._resolve_call(expr.func, cls)
            if target is not None:
                return self.view.context_managers.get(target)
        return None

    def _resolve_call(self, func, cls: str) -> Optional[_Fn]:
        if isinstance(func, ast.Name):
            return ("", func.id)  # module fn; validated at link time
        if not isinstance(func, ast.Attribute):
            return None
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls:
                return (cls, func.attr)
            rcls = self.view.receiver_names.get(recv.id)
            return None if rcls is None else (rcls, func.attr)
        if isinstance(recv, ast.Attribute):
            rcls = self.view.receiver_attrs.get(recv.attr)
            return None if rcls is None else (rcls, func.attr)
        if isinstance(recv, ast.Call):
            inner = self._resolve_call(recv.func, cls)
            if inner is not None:
                ret = self.view.factory_returns.get(inner)
                if ret is not None:
                    return (ret, func.attr)
        return None

    def _walk(self, stmts, relpath, cls, held, events) -> None:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                added = []
                for item in st.items:
                    # earlier items of a multi-item `with a, b:` are
                    # already held when later items acquire
                    held_now = held + tuple(added)
                    lid = self._resolve_lock(item.context_expr,
                                             relpath, cls)
                    if lid is not None:
                        events.append((held_now, "acquire", lid))
                        added.append(lid)
                    else:
                        self._calls_in(item.context_expr, cls,
                                       held_now, events)
                self._walk(st.body, relpath, cls,
                           held + tuple(added), events)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs run with no inherited hold
            self._calls_in(st, cls, held, events,
                           skip_bodies=True)
            for name in ("body", "orelse", "finalbody"):
                body = getattr(st, name, None)
                if body:
                    self._walk(body, relpath, cls, held, events)
            for h in getattr(st, "handlers", []) or []:
                self._walk(h.body, relpath, cls, held, events)

    def _calls_in(self, node, cls, held, events,
                  skip_bodies: bool = False) -> None:
        """Record resolvable calls in this statement's expressions
        (not its nested statement bodies — the walker recurses those
        with the right held set)."""
        skip = set()
        if skip_bodies:
            for name in ("body", "orelse", "finalbody"):
                for sub in getattr(node, name, None) or []:
                    skip.update(id(x) for x in ast.walk(sub))
            for h in getattr(node, "handlers", []) or []:
                for sub in h.body:
                    skip.update(id(x) for x in ast.walk(sub))
        for sub in ast.walk(node):
            if id(sub) in skip or not isinstance(sub, ast.Call):
                continue
            target = self._resolve_call(sub.func, cls)
            if target is not None:
                events.append((held, "call", target))

    # -- linking + verdicts -------------------------------------------------

    def _link(self, fn: _Fn) -> Optional[_Fn]:
        """Resolve a call target to a summarized function (module-fn
        names link only when unique)."""
        cls, name = fn
        if cls == "":
            return self._module_fns.get(name)
        return fn if fn in self._events else None

    def _acq_closure(self) -> Dict[_Fn, Set[str]]:
        acq: Dict[_Fn, Set[str]] = {
            fn: {payload for _, kind, payload in events
                 if kind == "acquire"}
            for fn, events in self._events.items()}
        changed = True
        while changed:
            changed = False
            for fn, events in self._events.items():
                for _, kind, payload in events:
                    if kind != "call":
                        continue
                    callee = self._link(payload)
                    if callee is None or callee not in acq:
                        continue
                    before = len(acq[fn])
                    acq[fn] |= acq[callee]
                    changed = changed or len(acq[fn]) != before
        return acq

    def edges(self) -> Dict[Tuple[str, str], str]:
        """(held, acquired) -> first 'where' seen (extracted +
        declared EXTRA_EDGES)."""
        acq = self._acq_closure()
        out: Dict[Tuple[str, str], str] = {}
        for fn, events in self._events.items():
            where = self._where[fn]
            for held, kind, payload in events:
                if not held:
                    continue
                if kind == "acquire":
                    inner = {payload}
                else:
                    callee = self._link(payload)
                    inner = acq.get(callee, set()) if callee else set()
                for h in held:
                    for m in inner:
                        out.setdefault((h, m), where)
        for a, b, why in self.view.extra_edges:
            out.setdefault((a, b), f"EXTRA_EDGES: {why}")
        return out

    def finish(self) -> Tuple[Dict[Tuple[str, str], str],
                              List[Tuple[str, int, str, str]]]:
        edges = self.edges()
        violations: List[Tuple[str, int, str, str]] = []
        reg_path = "spark_tpu/analysis/concurrency/registry.py"
        for (a, b), where in sorted(edges.items()):
            if a == b:
                if self.view.kind_of(a) != "rlock":
                    violations.append((
                        reg_path, 1, CODE_CYCLE,
                        f"self-deadlock: non-reentrant lock {a!r} "
                        f"acquired while already held ({where})"))
                continue
            ra, rb = self.view.rank_of(a), self.view.rank_of(b)
            if ra is None or rb is None:
                continue  # unregistered ends are GB104's finding
            if ra >= rb:
                violations.append((
                    reg_path, 1, CODE_RANK,
                    f"lock-order inversion: {a!r} (rank {ra}) held "
                    f"while acquiring {b!r} (rank {rb}) at {where} — "
                    f"edges must ascend in rank or the ranking must "
                    f"change (with every OTHER nesting re-checked)"))
        for cycle in self._cycles({e for e in edges if e[0] != e[1]}):
            violations.append((
                reg_path, 1, CODE_CYCLE,
                f"lock-order cycle (potential deadlock): "
                f"{' -> '.join(cycle + (cycle[0],))}"))
        return edges, violations

    @staticmethod
    def _cycles(edge_set: Set[Tuple[str, str]]) -> List[Tuple[str, ...]]:
        graph: Dict[str, List[str]] = {}
        for a, b in sorted(edge_set):
            graph.setdefault(a, []).append(b)
        seen: Set[str] = set()
        cycles: List[Tuple[str, ...]] = []

        def dfs(node, stack, on_stack):
            seen.add(node)
            on_stack[node] = len(stack)
            stack.append(node)
            for nxt in graph.get(node, ()):
                if nxt in on_stack:
                    cycles.append(tuple(stack[on_stack[nxt]:]))
                elif nxt not in seen:
                    dfs(nxt, stack, on_stack)
            stack.pop()
            del on_stack[node]

        for start in sorted(graph):
            if start not in seen:
                dfs(start, [], {})
        return cycles


def build_graph(repo: str, view: Optional[RegistryView] = None
                ) -> Tuple[Dict[Tuple[str, str], str],
                           List[Tuple[str, int, str, str]]]:
    """Convenience: parse the repository's scanned modules and return
    (edges, violations) — tests and lockwatch consumers use this."""
    import os
    analysis = LockOrderAnalysis(view)
    for relpath in sorted(analysis.view.scanned_relpaths()):
        path = os.path.join(repo, relpath)
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        analysis.add_file(relpath, tree)
    return analysis.finish()

"""Concurrency analyzer: guarded-by lint, lock-order graph, lockwatch.

PRs 6-11 turned the engine into a genuinely multithreaded system —
HTTP handler threads in the SQL service, per-session worker execution,
the ingest-prefetch daemon, the listener bus feeding straggler /
rebalance consumers — and the lock discipline that keeps it correct
(metrics inc locks, the FaultPlan.fire guard, the device-cache RLock)
was retrofitted by review-pass hand-audit. This package turns that
discipline into STATIC CHECKS over one declarative registry
(registry.py), the same shape the fault-site lint gave chaos seams:

- ``guarded-by`` (guarded.py): every declared shared mutable attribute
  is written only inside a ``with <declared lock>`` block; every
  ``threading.Lock/RLock/Condition`` in the engine is registered (with
  a deadlock-avoidance rank); every lock-owning class fully declares
  its shared state; ContextVar-backed state is recognized as
  thread-confined; intentional benign races carry an explicit waiver
  with a reviewer-visible reason.
- ``lock-order`` (lockorder.py): the static lock-acquisition graph —
  lexically nested ``with`` blocks plus resolvable call-graph edges —
  must be acyclic AND consistent with the ranks declared in the
  registry (every edge ascends; the ranks ARE the canonical order).

The runtime half lives in ``spark_tpu.testing.lockwatch``: wrapped
locks record the ACTUAL acquisition order, hold times and contention
under the concurrent stress test, and assert the observed order is
consistent with the same registry the static passes prove acyclic.

Known limitation (by design, documented here once): the write-site
check tracks ``self.<attr>`` targets plus the small set of named
receivers in ``registry.RECEIVER_NAMES``; a mutation through a local
alias (``held = self._leases[o]; held[k] = v``) is invisible to it.
Every such alias site in the tree sits inside the owning lock's
``with`` block today; lockwatch is the dynamic backstop.
"""

from .registry import (CONFINED, EXTRA_EDGES, GUARDED_BY, LOCKS,
                       MODULE_WAIVERS, WAIVERS, kind_of, lock_ids,
                       rank_of)

__all__ = ["LOCKS", "GUARDED_BY", "WAIVERS", "CONFINED",
           "MODULE_WAIVERS", "EXTRA_EDGES", "rank_of", "kind_of",
           "lock_ids"]

"""LintPass adapters for the concurrency analyses.

Registered into the unified lint framework (scripts/lint.py --all,
preflight, tests/test_analysis.py clean-tree gate) alongside the
metric-prefix / conf-key / fault-site / tracer-leak passes. The real
logic lives in guarded.py / lockorder.py as injectable-registry
libraries so tests can run them against synthetic trees and synthetic
declarations.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..lints import LintContext, LintPass, register_lint
from .guarded import GuardedAnalysis
from .lockorder import LockOrderAnalysis


@register_lint
class GuardedByPass(LintPass):
    """Shared mutable state is inventoried and written under its
    declared lock (analysis/concurrency/registry.py): declaration <->
    lock object <-> write sites, three ways. ContextVar-backed state
    is thread-confined; intentional benign races carry waivers whose
    reasons are surfaced in the lint output."""

    name = "guarded-by"
    doc = "shared-state writes hold their GUARDED_BY-declared lock"
    code = "GB100"

    def __init__(self):
        self._analysis = GuardedAnalysis()

    def scope(self, relpath: str) -> bool:
        # every spark_tpu file: lock creations must be registered
        # anywhere; write checks apply inside the registry's modules
        return relpath.startswith("spark_tpu/")

    def check(self, tree: ast.Module, relpath: str,
              ctx: LintContext) -> List[Tuple[int, str]]:
        self._analysis.add_file(relpath, tree)
        return []

    def finish(self, ctx: LintContext):
        out = [(relpath, line, msg, code)
               for relpath, line, code, msg in self._analysis.finish()]
        ctx.notes.extend(self._analysis.notes())
        return out


@register_lint
class LockOrderPass(LintPass):
    """The static lock-acquisition graph (nested `with` + resolvable
    call-graph edges + declared EXTRA_EDGES) is acyclic and every edge
    ascends in registry rank — the canonical order lockwatch asserts
    at runtime."""

    name = "lock-order"
    doc = "static lock-acquisition graph is acyclic and rank-ascending"
    code = "LO200"

    def __init__(self):
        self._analysis = LockOrderAnalysis()

    def scope(self, relpath: str) -> bool:
        return relpath in self._analysis.view.scanned_relpaths()

    def check(self, tree: ast.Module, relpath: str,
              ctx: LintContext) -> List[Tuple[int, str]]:
        self._analysis.add_file(relpath, tree)
        return []

    def finish(self, ctx: LintContext):
        edges, violations = self._analysis.finish()
        verdict = "acyclic, rank-ascending" if not violations else \
            f"{len(violations)} ORDER VIOLATION(S)"
        ctx.notes.append(
            f"lock-order: {len(edges)} static acquisition edges over "
            f"{len(self._analysis.view.locks)} registered locks "
            f"({verdict})")
        return [(relpath, line, msg, code)
                for relpath, line, code, msg in violations]

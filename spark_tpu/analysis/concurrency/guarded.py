"""Guarded-by analysis: declared shared state is written under its lock.

Three-way check, mirroring the fault-site lint:

1. declaration -> lock object: every `LockDecl` matches a real
   `threading.Lock()/RLock()/Condition()` creation site, and every
   creation site in `spark_tpu/` is declared (GB104/GB105) — a new
   lock cannot ship unranked;
2. declaration -> state: every `GuardDecl`/`Waiver` names a class and
   attribute that actually exist (GB103) — the registry cannot go
   stale;
3. state -> use sites: every write to a declared attribute outside
   `__init__` sits inside `with <declared lock>` (GB101), and every
   OTHER instance-attribute write in a shared class is either
   declared, waived, or a finding (GB102) — shared mutable state must
   be inventoried, not discovered in an incident.

Thread-confined state is exempt two ways: classes declared
`ConfinedDecl` (ContextVar-installed / single-consumer instances),
and module globals initialized from `ContextVar(...)`, which the
scanner recognizes automatically.

Write detection covers `self.<attr> = / += / del`, subscript stores
`self.<attr>[k] = v`, mutating method calls (`.append`, `.pop`,
`.setdefault`, ...), the same shapes through the registry's named
receivers (`entry.current_record = ...`), and module globals (both
`global X` rebinds and mutator calls on module-level collection
literals). Mutations through local aliases are out of scope — see the
package docstring.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import registry as _reg

#: method names that mutate their receiver in place
MUTATORS = frozenset({
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "move_to_end",
    "appendleft", "popleft", "sort", "reverse",
})

#: methods exempt from write checks (construction happens-before)
INIT_METHODS = ("__init__", "__new__", "__post_init__")

CODE_UNGUARDED = "GB101"
CODE_UNDECLARED = "GB102"
CODE_STALE_DECL = "GB103"
CODE_UNREG_LOCK = "GB104"
CODE_STALE_LOCK = "GB105"
CODE_EMPTY_WAIVER = "GB107"


@dataclass
class RegistryView:
    """The subset of the registry the analyses consult — injectable so
    tests can run the passes against synthetic declarations."""

    locks: tuple = _reg.LOCKS
    guards: tuple = _reg.GUARDED_BY
    waivers: tuple = _reg.WAIVERS
    confined: tuple = _reg.CONFINED
    receiver_names: dict = field(
        default_factory=lambda: dict(_reg.RECEIVER_NAMES))
    receiver_attrs: dict = field(
        default_factory=lambda: dict(_reg.RECEIVER_ATTRS))
    factory_returns: dict = field(
        default_factory=lambda: dict(_reg.FACTORY_RETURNS))
    context_managers: dict = field(
        default_factory=lambda: dict(_reg.CONTEXT_MANAGERS))
    extra_edges: tuple = _reg.EXTRA_EDGES
    held_callees: dict = field(
        default_factory=lambda: dict(_reg.CALLED_WITH_LOCK_HELD))

    # -- derived lookups ----------------------------------------------------

    def class_locks(self, relpath: str, cls: str) -> Dict[str, str]:
        return {d.attr: d.lock_id for d in self.locks
                if d.relpath == relpath and d.cls == cls}

    def guard_map(self, relpath: str, cls: str) -> Dict[str, str]:
        return {g.attr: g.lock for g in self.guards
                if g.relpath == relpath and g.cls == cls}

    def waived(self, relpath: str, cls: str) -> Set[str]:
        return {w.attr for w in self.waivers
                if w.relpath == relpath and w.cls == cls}

    def confined_classes(self, relpath: str) -> Set[str]:
        return {c.cls for c in self.confined if c.relpath == relpath}

    def shared_classes(self, relpath: str) -> Set[str]:
        """Classes the inventory applies to in this file: lock owners
        plus anything with guard or waiver declarations."""
        out = {d.cls for d in self.locks
               if d.relpath == relpath and d.cls}
        out |= {g.cls for g in self.guards
                if g.relpath == relpath and g.cls}
        out |= {w.cls for w in self.waivers
                if w.relpath == relpath and w.cls}
        return out

    def scanned_relpaths(self) -> Set[str]:
        return ({d.relpath for d in self.locks}
                | {g.relpath for g in self.guards}
                | {w.relpath for w in self.waivers}
                | {c.relpath for c in self.confined})

    def rank_of(self, lock_id: str) -> Optional[int]:
        for d in self.locks:
            if d.lock_id == lock_id:
                return d.rank
        return None

    def kind_of(self, lock_id: str) -> Optional[str]:
        for d in self.locks:
            if d.lock_id == lock_id:
                return d.kind
        return None


def _dotted(node: ast.expr) -> Optional[str]:
    """'self._lock' / 'entry.lock' / '_REGISTRY_LOCK' for simple
    name/attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _is_lock_ctor(call: ast.expr) -> Optional[str]:
    """'lock'/'rlock'/'condition' when `call` constructs one."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return {"Lock": "lock", "RLock": "rlock",
            "Condition": "condition"}.get(name)


def _is_contextvar_ctor(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    return (isinstance(fn, ast.Name) and fn.id == "ContextVar") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "ContextVar")


@dataclass
class _Write:
    """One detected mutation site."""

    relpath: str
    line: int
    cls: str            # owning class ("" = module global)
    attr: str
    held: Tuple[str, ...]  # dotted lock exprs held at the site
    via: str            # "assign" | "augassign" | "del" | mutator name
    receiver: str       # "self" | receiver name | "" (global)


class GuardedAnalysis:
    """Feed files with `add_file`, then `finish()` -> (violations,
    notes). Violations are (relpath, line, code, message)."""

    def __init__(self, view: Optional[RegistryView] = None):
        self.view = view or RegistryView()
        #: (relpath, cls, attr) -> (line, kind) for lock creations
        self.lock_creations: Dict[Tuple[str, str, str],
                                  Tuple[int, str]] = {}
        #: (relpath, cls) -> attrs assigned anywhere (incl __init__)
        self.assigned: Dict[Tuple[str, str], Set[str]] = {}
        self.writes: List[_Write] = []
        self.violations: List[Tuple[str, int, str, str]] = []
        self._seen_files: Set[str] = set()

    # -- per-file -----------------------------------------------------------

    def add_file(self, relpath: str, tree: ast.Module) -> None:
        self._seen_files.add(relpath)
        in_scope = relpath in self.view.scanned_relpaths()
        module_globals = self._module_globals(tree)
        # module-level lock creations + global write checks
        self._scan_module_level(relpath, tree, module_globals, in_scope)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(relpath, node, in_scope,
                                 module_globals)
        # lock creations can hide inside nested defs/classes too
        self._scan_all_lock_creations(relpath, tree)

    def _module_globals(self, tree: ast.Module) -> Dict[str, str]:
        """Module-level names -> 'contextvar' | 'collection' | 'other'
        (what the global-write checks key on)."""
        out: Dict[str, str] = {}
        for node in tree.body:
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if _is_contextvar_ctor(value):
                    out[t.id] = "contextvar"
                elif isinstance(value, (ast.Dict, ast.List, ast.Set)) \
                        or (isinstance(value, ast.Call)
                            and isinstance(value.func, ast.Name)
                            and value.func.id in ("dict", "list", "set",
                                                  "OrderedDict")):
                    out[t.id] = "collection"
                else:
                    out[t.id] = "other"
        return out

    def _scan_all_lock_creations(self, relpath: str,
                                 tree: ast.Module) -> None:
        """Find every lock construction, attributed to (class, attr)
        for `self.X = threading.Lock()` inside a class, or ("", name)
        for module-level `X = threading.Lock()`."""
        def scan(node, cls: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    # only attribute top-level classes; nested classes
                    # keep the outer attribution off (rare, and their
                    # locks still get flagged under the outer class)
                    scan(child, child.name if cls == "" else cls)
                    continue
                if isinstance(child, ast.Assign):
                    kind = _is_lock_ctor(child.value)
                    if kind is not None:
                        for t in child.targets:
                            d = _dotted(t)
                            if d is None:
                                continue
                            if d.startswith("self."):
                                key = (relpath, cls, d[5:])
                            elif "." not in d:
                                key = (relpath, "" if cls == "" else cls,
                                       d)
                            else:
                                continue
                            self.lock_creations.setdefault(
                                key, (child.lineno, kind))
                scan(child, cls)

        scan(tree, "")

    def _scan_module_level(self, relpath: str, tree: ast.Module,
                           module_globals: Dict[str, str],
                           in_scope: bool) -> None:
        if not in_scope:
            return
        guard = self.view.guard_map(relpath, "")
        waived = self.view.waived(relpath, "")
        # top-level functions + class methods ONLY: _walk already
        # recurses nested defs, so walking every FunctionDef ast.walk
        # yields would double-report violations inside nested functions
        funcs = [n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        for cls_node in tree.body:
            if isinstance(cls_node, ast.ClassDef):
                funcs += [n for n in cls_node.body
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
        for node in funcs:
            gnames = {n for st in ast.walk(node)
                      if isinstance(st, ast.Global) for n in st.names}
            self._walk(node.body, relpath, "", frozenset(),
                       watch_globals=gnames | {
                           n for n, k in module_globals.items()
                           if k == "collection" or n in guard
                           or n in waived},
                       module_globals=module_globals,
                       guard=guard, waived=waived,
                       confined_globals={
                           n for n, k in module_globals.items()
                           if k == "contextvar"},
                       exempt=False, shared=True)

    def _scan_class(self, relpath: str, node: ast.ClassDef,
                    in_scope: bool,
                    module_globals: Dict[str, str]) -> None:
        cls = node.name
        assigned = self.assigned.setdefault((relpath, cls), set())
        for sub in ast.walk(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                d = _dotted(t)
                if d is not None and d.startswith("self.") \
                        and d.count(".") == 1:
                    assigned.add(d[5:])
            # dataclass-style class-body annotations count as existing
            if isinstance(sub, ast.AnnAssign) \
                    and isinstance(sub.target, ast.Name):
                assigned.add(sub.target.id)
        if not in_scope:
            return
        shared = cls in self.view.shared_classes(relpath)
        confined = cls in self.view.confined_classes(relpath)
        if confined or not shared:
            # confined classes skip write checks; non-inventoried
            # classes are out of scope (receiver-writes into them are
            # handled from the writing file)
            return
        guard = self.view.guard_map(relpath, cls)
        waived = self.view.waived(relpath, cls)
        lock_attrs = set(self.view.class_locks(relpath, cls))
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            exempt = meth.name in INIT_METHODS
            held0 = frozenset()
            held_lock = self.view.held_callees.get(
                (relpath, cls, meth.name))
            if held_lock is not None:
                held0 = frozenset({f"self.{held_lock}"})
            self._walk(meth.body, relpath, cls, held0,
                       watch_globals=set(), module_globals={},
                       guard=guard, waived=waived | lock_attrs,
                       confined_globals=set(), exempt=exempt,
                       shared=True)

    # -- statement walker with a held-locks stack ---------------------------

    def _walk(self, stmts, relpath, cls, held, *, watch_globals,
              module_globals, guard, waived, confined_globals, exempt,
              shared) -> None:
        kw = dict(watch_globals=watch_globals,
                  module_globals=module_globals, guard=guard,
                  waived=waived, confined_globals=confined_globals,
                  exempt=exempt, shared=shared)
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                added = set()
                for item in st.items:
                    # earlier items of a multi-item `with a, b:` are
                    # already held while later items evaluate
                    self._exprs(item.context_expr, relpath, cls,
                                held | added, **kw)
                    d = _dotted(item.context_expr)
                    if d is not None:
                        added.add(d)
                self._walk(st.body, relpath, cls, held | added, **kw)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, on an unknown thread with
                # no inherited lock: conservative empty held set
                self._walk(st.body, relpath, cls, frozenset(), **kw)
                continue
            if isinstance(st, ast.ClassDef):
                continue
            # this statement's own effects
            self._stmt(st, relpath, cls, held, **kw)
            # recurse into nested statement bodies
            for name in ("body", "orelse", "finalbody"):
                body = getattr(st, name, None)
                if body:
                    self._walk(body, relpath, cls, held, **kw)
            for h in getattr(st, "handlers", []) or []:
                self._walk(h.body, relpath, cls, held, **kw)

    def _stmt(self, st, relpath, cls, held, **kw) -> None:
        targets = []
        via = "assign"
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, ast.AugAssign):
            targets, via = [st.target], "augassign"
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            targets = [st.target]
        elif isinstance(st, ast.Delete):
            targets, via = st.targets, "del"
        for t in targets:
            self._target(t, relpath, cls, held, via, **kw)
        # mutator calls in this statement's OWN expressions — nested
        # statement bodies are excluded: the walker revisits them with
        # the correct held set (a `with self._lock:` inside a try arm
        # must not be scanned lock-less from the Try node)
        skip = set()
        for name in ("body", "orelse", "finalbody"):
            for sub in getattr(st, name, None) or []:
                skip.update(id(x) for x in ast.walk(sub))
        for h in getattr(st, "handlers", []) or []:
            for sub in h.body:
                skip.update(id(x) for x in ast.walk(sub))
        for sub in ast.walk(st):
            if id(sub) in skip:
                continue
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in MUTATORS:
                d = _dotted(sub.func.value)
                if d is not None:
                    self._write(relpath, sub.lineno, cls, d, held,
                                sub.func.attr, **kw)

    def _exprs(self, expr, relpath, cls, held, **kw) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in MUTATORS:
                d = _dotted(sub.func.value)
                if d is not None:
                    self._write(relpath, sub.lineno, cls, d, held,
                                sub.func.attr, **kw)

    def _target(self, t, relpath, cls, held, via, **kw) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, relpath, cls, held, via, **kw)
            return
        if isinstance(t, ast.Subscript):
            d = _dotted(t.value)
        else:
            d = _dotted(t)
        if d is not None:
            self._write(relpath, t.lineno, cls, d, held, via, **kw)

    # -- write classification ----------------------------------------------

    def _write(self, relpath, line, cls, dotted, held, via, *,
               watch_globals, module_globals, guard, waived,
               confined_globals, exempt, shared) -> None:
        if exempt or not shared:
            return
        parts = dotted.split(".")
        if parts[0] == "self" and len(parts) == 2 and cls:
            self._check_attr(relpath, line, cls, parts[1], held, via,
                             receiver="self")
        elif cls == "" and len(parts) == 1:
            name = parts[0]
            if name in confined_globals:
                return  # ContextVar-backed: thread-confined by design
            if name not in watch_globals:
                return
            self._check_attr(relpath, line, "", name, held, via,
                             receiver="")
        elif len(parts) == 2 and parts[0] in self.view.receiver_names:
            # `entry.current_record = ...` — resolve the receiver to
            # its declaring class and apply that class's rules
            rcls = self.view.receiver_names[parts[0]]
            for g in self.view.guards:
                if g.cls == rcls and g.attr == parts[1]:
                    self._check_attr(g.relpath, line, rcls, parts[1],
                                     held, via, receiver=parts[0],
                                     at_relpath=relpath)
                    return
            for w in self.view.waivers:
                if w.cls == rcls and w.attr == parts[1]:
                    self.writes.append(_Write(relpath, line, rcls,
                                              parts[1], tuple(held),
                                              via, parts[0]))
                    return
            for d in self.view.locks:
                if d.cls == rcls:
                    self.violations.append((
                        relpath, line, CODE_UNDECLARED,
                        f"write to {rcls}.{parts[1]} (via receiver "
                        f"{parts[0]!r}) is not declared in GUARDED_BY "
                        f"or waived — shared state must be "
                        f"inventoried"))
                    return

    def _check_attr(self, relpath, line, cls, attr, held, via, *,
                    receiver, at_relpath=None) -> None:
        at = at_relpath or relpath
        guard = self.view.guard_map(relpath, cls)
        waived = self.view.waived(relpath, cls)
        lock_attrs = set(self.view.class_locks(relpath, cls))
        self.writes.append(_Write(at, line, cls, attr, tuple(held),
                                  via, receiver))
        if attr in waived:
            return
        if attr in guard:
            lock = guard[attr]
            want = f"{receiver}.{lock}" if receiver else lock
            if want not in held:
                label = f"{cls}.{attr}" if cls else attr
                self.violations.append((
                    at, line, CODE_UNGUARDED,
                    f"unguarded write to {label} (via {via}): "
                    f"GUARDED_BY declares lock {lock!r} but it is not "
                    f"held here (held: {sorted(held) or 'none'})"))
            return
        if receiver == "self" and attr in lock_attrs:
            return  # handled by the creation-site checks
        label = f"{cls}.{attr}" if cls else f"module global {attr}"
        self.violations.append((
            at, line, CODE_UNDECLARED,
            f"write to {label} (via {via}) is not declared in "
            f"GUARDED_BY, waived, or thread-confined — add a "
            f"GuardDecl, a Waiver with a reason, or a ConfinedDecl "
            f"(registry.py)"))

    # -- whole-tree verdicts ------------------------------------------------

    def finish(self) -> List[Tuple[str, int, str, str]]:
        v = self.view
        out = list(self.violations)
        # lock object <-> declaration, both directions
        declared = {(d.relpath, d.cls, d.attr): d for d in v.locks}
        for key, (line, kind) in self.lock_creations.items():
            if key not in declared:
                relpath, cls, attr = key
                label = f"{cls}.{attr}" if cls else attr
                out.append((relpath, line, CODE_UNREG_LOCK,
                            f"unregistered {kind}: {label} has no "
                            f"LockDecl (analysis/concurrency/"
                            f"registry.py) — every lock needs an "
                            f"acquisition-order rank"))
        for key, d in declared.items():
            if key not in self.lock_creations:
                out.append((d.relpath, 1, CODE_STALE_LOCK,
                            f"stale LockDecl {d.lock_id!r}: no "
                            f"threading.{d.kind} creation for "
                            f"{d.cls or '<module>'}.{d.attr} found"))
        # guard/waiver declarations name real state + a real lock
        for g in v.guards:
            locks = v.class_locks(g.relpath, g.cls)
            if g.lock not in locks:
                out.append((g.relpath, 1, CODE_STALE_DECL,
                            f"GuardDecl for {g.cls or '<module>'}."
                            f"{g.attr} names lock {g.lock!r} which has "
                            f"no LockDecl on that class"))
            if g.cls and g.attr not in self.assigned.get(
                    (g.relpath, g.cls), set()):
                out.append((g.relpath, 1, CODE_STALE_DECL,
                            f"stale GuardDecl: {g.cls}.{g.attr} is "
                            f"never assigned in the class"))
        for w in v.waivers:
            if not w.reason.strip():
                out.append((w.relpath, 1, CODE_EMPTY_WAIVER,
                            f"waiver for {w.cls or '<module>'}."
                            f"{w.attr} has no justification reason"))
            if w.cls and (w.relpath, w.cls) in self.assigned \
                    and w.attr not in self.assigned[(w.relpath, w.cls)]:
                out.append((w.relpath, 1, CODE_STALE_DECL,
                            f"stale Waiver: {w.cls}.{w.attr} is never "
                            f"assigned in the class"))
        return out

    def notes(self) -> List[str]:
        """The reviewer-visible waiver list (lint output + --json)."""
        out = []
        for w in self.view.waivers:
            label = f"{w.cls}.{w.attr}" if w.cls else w.attr
            out.append(f"waiver: {w.relpath}: {label} — {w.reason}")
        for c in self.view.confined:
            out.append(f"confined: {c.relpath}: {c.cls} — {c.reason}")
        return out

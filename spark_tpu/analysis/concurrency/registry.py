"""THE declarative concurrency registry: locks, guards, waivers.

One table of record for the engine's thread-shared state, mirroring
`testing.faults.KNOWN_SITES` for chaos seams: the guarded-by and
lock-order passes check the DECLARATIONS here against the CODE three
ways (declaration <-> lock object <-> use sites), and the runtime
lockwatch asserts observed acquisition order against the same ranks.

Thread roots (what makes state here "shared"):

- HTTP handler threads (`service/server.py` ThreadingHTTPServer) and
  async-submit worker threads, one per in-flight request;
- per-session execution serialized under the session lease
  (`service.session` — the outermost lock, rank 10);
- the ingest-prefetch daemon (`io/sources.py` PrefetchChunkIterator
  worker), which fires fault seams and counts registry metrics;
- the listener bus delivering to the event-log / metrics / straggler /
  rebalancer subscribers (synchronously, on whichever thread posts).

RANKS define the canonical acquisition order: a thread holding a lock
may only acquire locks of STRICTLY HIGHER rank. The static lock-order
pass proves every extracted edge ascends (hence the graph is acyclic);
lockwatch proves the observed runtime edges do too. To register a new
lock: create it, add a LockDecl with a rank consistent with every
nesting it participates in, declare the attributes it guards
(GuardDecl) or waive them with a reason, and — if it can nest with
existing locks in code the static extractor cannot resolve — add the
edge to EXTRA_EDGES with a comment. The guarded-by pass fails until
all three are done.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class LockDecl:
    """One registered lock: where it lives, what it is, and its rank
    in the canonical acquisition order (lower = acquired first)."""

    lock_id: str
    relpath: str
    cls: str            # "" = module-level global
    attr: str
    kind: str           # "lock" | "rlock" | "condition"
    rank: int
    doc: str = ""


@dataclass(frozen=True)
class GuardDecl:
    """One shared mutable attribute and the lock that guards it (the
    lock attr must be a LockDecl on the same class/module)."""

    relpath: str
    cls: str            # "" = module-level global name in `attr`
    attr: str
    lock: str           # lock ATTRIBUTE name (e.g. "_lock"), not id


@dataclass(frozen=True)
class Waiver:
    """An intentionally-unguarded write site, with the reason the race
    is benign. Surfaced in the lint output (reviewer-visible)."""

    relpath: str
    cls: str
    attr: str
    reason: str


@dataclass(frozen=True)
class ConfinedDecl:
    """A class in a shared module whose instances never cross threads
    (ContextVar-installed / single-consumer): write checks skipped."""

    relpath: str
    cls: str
    reason: str


_SVC = "spark_tpu/service/"
_OBS = "spark_tpu/observability/"

#: every threading.Lock/RLock/Condition in spark_tpu/ must appear here
#: (the guarded-by pass fails both on an unregistered lock object and
#: on a stale declaration). Ranks: see module docstring.
LOCKS: Tuple[LockDecl, ...] = (
    LockDecl("service.stop", _SVC + "server.py", "SqlService",
             "_stop_lock", "lock", 8,
             "serializes stop() (idempotent, signal-safe) and guards "
             "the _stopped/_draining flags; ranked below everything "
             "stop() tears down (it nests service.install inside)"),
    LockDecl("service.session", _SVC + "pool.py", "_Entry", "lock",
             "lock", 10,
             "per-session execution lease; held across the whole query "
             "(outermost — everything below may nest inside it)"),
    LockDecl("service.fleet_inflight", _SVC + "fleet.py",
             "FleetSupervisor", "_cv", "condition", 12,
             "router in-flight proxied-request count + drain flag "
             "(cv: drain waits here for in-flight to reach zero); "
             "counter/flag ops only inside — proxy I/O, routing and "
             "metrics run OUTSIDE it"),
    LockDecl("service.fleet_worker", _SVC + "fleet.py", "_Worker",
             "_lock", "lock", 13,
             "per-worker lifecycle slice (state/port/proc/generation/"
             "restart bookkeeping), the streaming _TriggerStatus "
             "pattern: field ops only inside — spawn I/O, health "
             "probes, bundle dumps and metrics all run OUTSIDE it"),
    LockDecl("service.pool", _SVC + "pool.py", "SessionPool", "_lock",
             "lock", 14, "session-pool entry map"),
    LockDecl("service.quota", _SVC + "admission.py", "SessionQuota",
             "_lock", "lock", 16,
             "per-session in-flight quota counters; check-and-inc "
             "only, rejection bookkeeping runs outside it"),
    LockDecl("service.admission", _SVC + "admission.py",
             "AdmissionController", "_cv", "condition", 18,
             "execution-slot gate (cv: queued requests wait here)"),
    LockDecl("service.records", _SVC + "server.py", "SqlService",
             "_records_lock", "lock", 22, "service query registry"),
    LockDecl("service.async", _SVC + "server.py", "SqlService",
             "_async_lock", "lock", 23, "async in-flight bound"),
    LockDecl("service.install", _SVC + "server.py", "SqlService",
             "_install_lock", "lock", 24,
             "one-shot arbiter installation guard"),
    LockDecl("streaming.live", "spark_tpu/streaming.py", "",
             "_LIVE_LOCK", "lock", 25,
             "live trigger-loop registry (stream-<n> -> query): "
             "registered in start(), dropped by the loop's finally / "
             "stop(); dict ops only inside — per-query status rows "
             "build OUTSIDE it"),
    LockDecl("execution.lifecycle", "spark_tpu/execution/lifecycle.py",
             "", "_TOKENS_LOCK", "lock", 26,
             "cancel-token registry ((app_id, query_id) -> token): "
             "registered by the executor under the session lease, "
             "cancelled from any thread; dict ops only inside — "
             "token.cancel() (an Event.set) runs outside it"),
    LockDecl("streaming.trigger", "spark_tpu/streaming.py",
             "_TriggerStatus", "_lock", "lock", 27,
             "cross-thread status slice of a supervised streaming "
             "query (loop thread writes, service/stop() read); field "
             "ops only inside — seams, metrics and listener posts all "
             "fire OUTSIDE it"),
    LockDecl("service.arbiter", _SVC + "arbiter.py",
             "DeviceResourceArbiter", "_cv", "condition", 30,
             "HBM lease pool (cv: denied leases wait for releases)"),
    LockDecl("service.result_cache", _SVC + "arbiter.py", "ResultCache",
             "_lock", "lock", 34, "plan-fingerprint result LRU"),
    LockDecl("service.history", _SVC + "query_history.py",
             "QueryHistoryStore", "_lock", "lock", 36,
             "per-query detail store"),
    LockDecl("io.device_cache", "spark_tpu/io/device_cache.py",
             "DeviceTableCache", "_lock", "rlock", 40,
             "device table cache (rlock: arbiter eviction may reenter)"),
    LockDecl("obs.straggler", _OBS + "straggler.py", "StragglerMonitor",
             "_lock", "lock", 44, "rolling per-shard wait windows"),
    LockDecl("obs.status", _OBS + "status_store.py", "StatusStore",
             "_lock", "lock", 45,
             "status-store rings + session attribution; providers and "
             "metrics calls run OUTSIDE it (they take service-layer "
             "locks ranked below), so only dict/deque ops sit inside"),
    LockDecl("obs.flightrec", _OBS + "flight_recorder.py",
             "FlightRecorder", "_lock", "lock", 46,
             "flight-recorder rings + retained plan/span maps; dump "
             "file I/O and conf/metrics snapshots run OUTSIDE it over "
             "copies"),
    LockDecl("obs.bus", _OBS + "listener.py", "ListenerBus", "_lock",
             "lock", 48,
             "listener list + drop counter (delivery runs OUTSIDE it)"),
    LockDecl("obs.event_log", _OBS + "sinks.py", "EventLogListener",
             "_write_lock", "lock", 52, "event-log roll+append"),
    LockDecl("faults.plan", "spark_tpu/testing/faults.py", "FaultPlan",
             "_lock", "lock", 56,
             "hit counters (fault effects run OUTSIDE it)"),
    LockDecl("execution.compile_cache",
             "spark_tpu/execution/compile_cache.py", "CompileCache",
             "_lock", "lock", 58,
             "persistent compile cache: serializes entry publish, "
             "LRU eviction and manifest maintenance within a process "
             "(cross-process safety is atomic renames); pure file "
             "I/O inside — counters inc and fault seams fire OUTSIDE "
             "it, so nothing nests under it"),
    LockDecl("udf.pool", "spark_tpu/udf_worker/pool.py", "UdfWorkerPool",
             "_cv", "condition", 59,
             "UDF worker checkout/checkin (cv: checkouts beyond "
             "maxWorkers wait for a checkin); list/counter ops only "
             "inside — spawns, kills, chaos seams and lifecycle "
             "checkpoints all run OUTSIDE it (ranked above faults.plan "
             "so no seam may fire under it)"),
    LockDecl("metrics.registry", _OBS + "metrics.py", "MetricsRegistry",
             "_lock", "lock", 60, "metric instrument map"),
    LockDecl("metrics.flush", _OBS + "metrics.py", "MetricsRegistry",
             "_flush_lock", "lock", 62, "sink write serialization"),
    LockDecl("config.registry", "spark_tpu/config.py", "",
             "_REGISTRY_LOCK", "lock", 70, "conf-entry registration"),
    LockDecl("metrics.counter", _OBS + "metrics.py", "Counter", "_lock",
             "lock", 80, "per-counter read-modify-write (leaf)"),
    LockDecl("metrics.timer", _OBS + "metrics.py", "Timer", "_lock",
             "lock", 81, "per-timer observation (leaf)"),
    LockDecl("metrics.histogram", _OBS + "metrics.py", "Histogram",
             "_lock", "lock", 82,
             "per-histogram bucket counters (leaf; bucket index is "
             "computed before acquiring it)"),
    LockDecl("testing.lockwatch", "spark_tpu/testing/lockwatch.py",
             "LockWatch", "_mu", "lock", 95,
             "lockwatch's own recorder lock: acquired inside every "
             "watched acquire, so it ranks above everything and is "
             "never itself wrapped"),
)

#: shared mutable attribute -> its guarding lock. Every write site
#: outside __init__ must sit inside `with self.<lock>` (guarded-by
#: pass); every lock-owning class must cover ALL its mutated attrs
#: here or in WAIVERS.
GUARDED_BY: Tuple[GuardDecl, ...] = (
    # metrics
    GuardDecl(_OBS + "metrics.py", "Counter", "value", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Timer", "count", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Timer", "total_s", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Timer", "min_s", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Timer", "max_s", "_lock"),
    GuardDecl(_OBS + "metrics.py", "MetricsRegistry", "_counters",
              "_lock"),
    GuardDecl(_OBS + "metrics.py", "MetricsRegistry", "_gauges",
              "_lock"),
    GuardDecl(_OBS + "metrics.py", "MetricsRegistry", "_timers",
              "_lock"),
    GuardDecl(_OBS + "metrics.py", "MetricsRegistry", "_histograms",
              "_lock"),
    GuardDecl(_OBS + "metrics.py", "Histogram", "counts", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Histogram", "count", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Histogram", "total", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Histogram", "min_v", "_lock"),
    GuardDecl(_OBS + "metrics.py", "Histogram", "max_v", "_lock"),
    # device cache
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "_entries", "_lock"),
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "_pins", "_lock"),
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "_bytes", "_lock"),
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "hits", "_lock"),
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "misses", "_lock"),
    GuardDecl("spark_tpu/io/device_cache.py", "DeviceTableCache",
              "evictions", "_lock"),
    # arbiter + result cache
    GuardDecl(_SVC + "arbiter.py", "DeviceResourceArbiter", "_leases",
              "_cv"),
    GuardDecl(_SVC + "arbiter.py", "DeviceResourceArbiter", "_denied",
              "_cv"),
    GuardDecl(_SVC + "arbiter.py", "DeviceResourceArbiter", "_pins",
              "_cv"),
    GuardDecl(_SVC + "arbiter.py", "ResultCache", "_entries", "_lock"),
    GuardDecl(_SVC + "arbiter.py", "ResultCache", "_bytes", "_lock"),
    # admission
    GuardDecl(_SVC + "admission.py", "AdmissionController", "running",
              "_cv"),
    GuardDecl(_SVC + "admission.py", "AdmissionController", "queued",
              "_cv"),
    GuardDecl(_SVC + "admission.py", "SessionQuota", "_inflight",
              "_lock"),
    # pool / server / history
    GuardDecl(_SVC + "pool.py", "SessionPool", "_entries", "_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_records",
              "_records_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_seq", "_records_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_tokens",
              "_records_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_async_inflight",
              "_async_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_installed_arbiter",
              "_install_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_stopped",
              "_stop_lock"),
    GuardDecl(_SVC + "server.py", "SqlService", "_draining",
              "_stop_lock"),
    # fleet supervisor + per-worker slices
    GuardDecl(_SVC + "fleet.py", "FleetSupervisor", "_inflight", "_cv"),
    GuardDecl(_SVC + "fleet.py", "FleetSupervisor", "_draining", "_cv"),
    GuardDecl(_SVC + "fleet.py", "FleetSupervisor", "_stopped", "_cv"),
    GuardDecl(_SVC + "fleet.py", "FleetSupervisor", "_seq", "_cv"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "state", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "port", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "pid", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "proc", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "generation", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "policy", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "next_spawn_ts", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "spawn_deadline_ts",
              "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "ping_failures", "_lock"),
    GuardDecl(_SVC + "fleet.py", "_Worker", "crash_times", "_lock"),
    GuardDecl(_SVC + "query_history.py", "QueryHistoryStore",
              "_entries", "_lock"),
    # observability
    GuardDecl(_OBS + "straggler.py", "StragglerMonitor", "_waits",
              "_lock"),
    GuardDecl(_OBS + "straggler.py", "StragglerMonitor", "_hosts",
              "_lock"),
    GuardDecl(_OBS + "straggler.py", "StragglerMonitor", "_flagged",
              "_lock"),
    GuardDecl(_OBS + "listener.py", "ListenerBus", "_listeners",
              "_lock"),
    GuardDecl(_OBS + "listener.py", "ListenerBus", "dropped", "_lock"),
    # status store
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_series",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_inflight",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_sessions",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore",
              "_status_counts", "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_phase_totals",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_queries_total",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_heartbeats",
              "_lock"),
    GuardDecl(_OBS + "status_store.py", "StatusStore", "_providers",
              "_lock"),
    # flight recorder
    GuardDecl(_OBS + "flight_recorder.py", "FlightRecorder", "_rings",
              "_lock"),
    GuardDecl(_OBS + "flight_recorder.py", "FlightRecorder", "_plans",
              "_lock"),
    GuardDecl(_OBS + "flight_recorder.py", "FlightRecorder", "_trees",
              "_lock"),
    GuardDecl(_OBS + "flight_recorder.py", "FlightRecorder", "_spans",
              "_lock"),
    GuardDecl(_OBS + "flight_recorder.py", "FlightRecorder", "_seq",
              "_lock"),
    # udf worker pool
    GuardDecl("spark_tpu/udf_worker/pool.py", "UdfWorkerPool", "_idle",
              "_cv"),
    GuardDecl("spark_tpu/udf_worker/pool.py", "UdfWorkerPool", "_live",
              "_cv"),
    GuardDecl("spark_tpu/udf_worker/pool.py", "UdfWorkerPool", "_all",
              "_cv"),
    # faults
    GuardDecl("spark_tpu/testing/faults.py", "FaultPlan", "hits",
              "_lock"),
    GuardDecl("spark_tpu/testing/faults.py", "FaultPlan", "fired_log",
              "_lock"),
    # lockwatch recorder
    GuardDecl("spark_tpu/testing/lockwatch.py", "LockWatch",
              "edge_counts", "_mu"),
    GuardDecl("spark_tpu/testing/lockwatch.py", "LockWatch",
              "lock_stats", "_mu"),
    # config (module-level global)
    GuardDecl("spark_tpu/config.py", "", "_REGISTRY", "_REGISTRY_LOCK"),
    # lifecycle token registry (module-level global)
    GuardDecl("spark_tpu/execution/lifecycle.py", "", "_TOKENS",
              "_TOKENS_LOCK"),
    # streaming live registry (module-level globals) + trigger status
    GuardDecl("spark_tpu/streaming.py", "", "_LIVE", "_LIVE_LOCK"),
    GuardDecl("spark_tpu/streaming.py", "", "_LIVE_SEQ", "_LIVE_LOCK"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus", "status",
              "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus", "error",
              "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus", "ticks",
              "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus",
              "skipped_ticks", "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus", "restarts",
              "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus",
              "last_skew_ms", "_lock"),
    GuardDecl("spark_tpu/streaming.py", "_TriggerStatus", "trigger_ms",
              "_lock"),
)

#: intentionally-unguarded state, each with the reason the race is
#: benign. The lint surfaces this list verbatim (reviewer-visible);
#: the matching source sites carry inline justification comments.
WAIVERS: Tuple[Waiver, ...] = (
    Waiver(_OBS + "metrics.py", "Gauge", "value",
           "single attribute store, atomic under the GIL; readers "
           "tolerate a stale point-in-time value"),
    Waiver(_SVC + "arbiter.py", "DeviceResourceArbiter", "stage_cache",
           "plain dict with GIL-atomic get/set; worst case is a "
           "duplicate stage compile whose last write wins (keys are "
           "deterministic content hashes, both values equivalent)"),
    Waiver("spark_tpu/execution/compile_cache.py", "CachedStageFn",
           "_jit",
           "GIL-atomic store of a lazily-built jit fallback; a race "
           "builds a duplicate equivalent jit whose last write wins "
           "(the arbiter.stage_cache precedent, one level down)"),
    Waiver("spark_tpu/execution/compile_cache.py", "CachedStageFn",
           "_compiled",
           "GIL-atomic list append of a (signature, Compiled) pair; "
           "racing adds of the same signature at worst duplicate an "
           "equivalent executable — compiled_for returns the first "
           "match, and entries are never removed"),
    Waiver("spark_tpu/execution/compile_cache.py", "CachedStageFn",
           "_make_jit",
           "bind_builder only fills a None slot with an equivalent "
           "thunk (every binder closes over the same plan for this "
           "stage key); GIL-atomic store, last write wins"),
    Waiver(_SVC + "pool.py", "_Entry", "current_record",
           "written by the server only while holding this entry's "
           "session lease (service.session): single writer per leased "
           "session; the status listener reads on the same thread"),
    Waiver(_SVC + "pool.py", "_Entry", "init_error",
           "happens-before via the ready Event: written before "
           "ready.set(), read only after ready.wait()"),
    Waiver(_SVC + "server.py", "SqlService", "_httpd",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path"),
    Waiver(_SVC + "server.py", "SqlService", "_serve_thread",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path"),
    Waiver(_SVC + "server.py", "SqlService", "_warm_thread",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(); the thread itself only fills the "
           "arbiter's waived stage_cache dict"),
    Waiver(_SVC + "fleet.py", "FleetSupervisor", "_httpd",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path (the "
           "SqlService._httpd precedent)"),
    Waiver(_SVC + "fleet.py", "FleetSupervisor", "_serve_thread",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path"),
    Waiver(_SVC + "fleet.py", "FleetSupervisor", "_health_thread",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path"),
    Waiver(_OBS + "status_store.py", "StatusStore", "_thread",
           "lifecycle attr written by the owning control thread in "
           "start()/stop(), not on the request path (the "
           "SqlService._serve_thread precedent)"),
    Waiver(_OBS + "status_store.py", "StatusStore", "_stop_event",
           "threading.Event is internally synchronized; clear() runs "
           "in start() before the heartbeat thread exists, set() in "
           "stop() is the cross-thread signal it exists for"),
    # module-level globals (cls="" and attr=global name)
    Waiver("spark_tpu/testing/faults.py", "", "_PLAN",
           "atomic reference rebind at execute_batch entry / test "
           "reset; the armed plan's mutable state is lock-guarded "
           "(FaultPlan._lock) and per-thread suppression is a "
           "ContextVar, not a plan swap"),
    Waiver("spark_tpu/testing/faults.py", "", "_EXTRA_SITES",
           "test-only registration seam: mutated at test setup before "
           "the seams it names run concurrently"),
    Waiver(_SVC + "arbiter.py", "", "_ARBITER",
           "atomic reference rebind at service start/stop, before "
           "worker threads exist / after they drained"),
    Waiver("spark_tpu/execution/compile_cache.py", "", "_CACHES",
           "GIL-atomic dict get/set; a racing duplicate CompileCache "
           "for one dir is equivalent — all writes go through atomic "
           "renames and reads tolerate concurrent eviction, the two "
           "instances' locks merely guard their own bookkeeping"),
    Waiver("spark_tpu/testing/lockwatch.py", "LockWatch", "_installed",
           "mutated only by the test harness thread during "
           "install()/uninstall(), before/after the watched "
           "concurrency runs"),
    Waiver("spark_tpu/testing/lockwatch.py", "", "_CURRENT",
           "GIL-atomic reference rebind by the test harness thread in "
           "watch_attr()/uninstall(); the flight recorder's dump only "
           "reads a point-in-time reference"),
    Waiver("spark_tpu/udf_worker/pool.py", "UdfWorkerPool",
           "max_workers",
           "GIL-atomic scalar refresh from conf at each worker-mode "
           "evaluation entry (python_eval.session_pool); checkout "
           "reads a point-in-time bound"),
    Waiver("spark_tpu/udf_worker/pool.py", "UdfWorkerPool",
           "idle_timeout_ms",
           "GIL-atomic scalar refresh from conf, same discipline as "
           "max_workers"),
)

#: classes in shared modules whose instances are thread-confined —
#: ContextVar-installed per execution or single-consumer by design.
CONFINED: Tuple[ConfinedDecl, ...] = (
    ConfinedDecl("spark_tpu/io/sources.py", "PrefetchChunkIterator",
                 "consumer-thread confined: the worker receives plain "
                 "args; the only cross-thread channels are the size-1 "
                 "Queue and the stop Event"),
    ConfinedDecl(_OBS + "spans.py", "SpanRecorder",
                 "per-execution recorder owned by the driver thread of "
                 "its query"),
    ConfinedDecl(_OBS + "spans.py", "ShardStreamTelemetry",
                 "ContextVar-installed per execution; buffered and "
                 "flushed on the driver thread"),
    ConfinedDecl("spark_tpu/parallel/elastic.py", "RebalanceState",
                 "ContextVar-installed per stream; on_straggler posts "
                 "synchronously on the driver thread"),
    ConfinedDecl("spark_tpu/udf_worker/pool.py", "WorkerHandle",
                 "checked out to exactly one query thread at a time; "
                 "the hand-off back into the pool's idle list happens "
                 "under the pool cv, which orders the threads"),
)

#: module-level global waivers live in WAIVERS with cls="". This alias
#: keeps call sites explicit about which kind they consult.
MODULE_WAIVERS = tuple(w for w in WAIVERS if w.cls == "")


# ---------------------------------------------------------------------------
# Call-resolution tables for the static lock-order extractor
# ---------------------------------------------------------------------------

#: bare local/module names the extractor may treat as instances of a
#: known class (kept deliberately tiny: every entry is an idiomatic,
#: unambiguous name in the scanned modules)
RECEIVER_NAMES: Dict[str, str] = {
    "CACHE": "DeviceTableCache",     # io.device_cache module singleton
    "entry": "_Entry",               # pool/server session-entry idiom
}

#: attribute names (the final `.attr` of a receiver chain) resolved to
#: a known class — `self.metrics.counter(...)`, `svc.pool...`
RECEIVER_ATTRS: Dict[str, str] = {
    "metrics": "MetricsRegistry",
    "_metrics": "MetricsRegistry",
    "admission": "AdmissionController",
    "_ctl": "AdmissionController",
    "session_quota": "SessionQuota",
    "arbiter": "DeviceResourceArbiter",
    "result_cache": "ResultCache",
    "history": "QueryHistoryStore",
    "_history": "QueryHistoryStore",
    "pool": "SessionPool",
    "bus": "ListenerBus",
    "listeners": "ListenerBus",
    "status_store": "StatusStore",
    "_store": "StatusStore",
}

#: factory methods whose RETURN value is an instance of another known
#: class (`self.metrics.counter(name).inc(...)` chains)
FACTORY_RETURNS: Dict[Tuple[str, str], str] = {
    ("MetricsRegistry", "counter"): "Counter",
    ("MetricsRegistry", "timer"): "Timer",
    ("MetricsRegistry", "gauge"): "Gauge",
    ("MetricsRegistry", "histogram"): "Histogram",
}

#: `with <recv>.<method>(...):` context managers that hold a
#: registered lock over their body
CONTEXT_MANAGERS: Dict[Tuple[str, str], str] = {
    ("AdmissionController", "slot"): "service.admission",
}

#: helper methods whose CONTRACT is "called with this lock held" (the
#: lexical `with` lives in the caller). The guarded-by pass treats the
#: lock as held throughout; the lock-order pass charges the callee's
#: acquisitions against it. Keyed (relpath, cls, method) -> lock attr.
CALLED_WITH_LOCK_HELD: Dict[Tuple[str, str, str], str] = {
    ("spark_tpu/observability/straggler.py", "StragglerMonitor",
     "_evaluate"): "_lock",
    # checkout's reap step: the lexical `with self._cv` lives in
    # checkout; _reap_locked only mutates _idle/_live under it
    ("spark_tpu/udf_worker/pool.py", "UdfWorkerPool",
     "_reap_locked"): "_cv",
}

#: acquisition-order edges the lexical extractor cannot see (locks
#: held across function boundaries, unresolvable indirect calls).
#: Each entry asserts "the left lock may be held while the right one
#: is acquired" and must ascend in rank like any extracted edge.
EXTRA_EDGES: Tuple[Tuple[str, str, str], ...] = (
    # the session lease is held across the entire submit body
    # (acquired in SqlService._lock_session, released in the caller's
    # finally) — everything the engine takes nests inside it
    ("service.session", "service.admission", "submit holds the lease "
     "while entering the admission slot"),
    ("service.session", "service.records", "admission on_event -> "
     "SqlService._post -> get_query, under the lease"),
    ("service.session", "service.arbiter", "engine execution leases "
     "HBM under the session lease"),
    ("service.session", "service.result_cache", "result-cache "
     "fill/probe during execution"),
    ("service.session", "service.history", "status listener stores "
     "detail at query end"),
    ("service.session", "io.device_cache", "scan loads fill the "
     "device cache during execution"),
    ("service.session", "obs.straggler", "mesh telemetry posts "
     "on_shard_records during execution"),
    ("service.session", "obs.bus", "lifecycle events post on the "
     "session bus during execution"),
    ("service.session", "obs.event_log", "event-log append at query "
     "end"),
    ("service.session", "faults.plan", "chaos seams fire during "
     "execution"),
    ("service.session", "execution.compile_cache", "stage compiles "
     "publish serialized executables under the lease"),
    ("service.session", "metrics.registry", "metric lookups during "
     "execution"),
    ("service.session", "metrics.flush", "sink flush at query end"),
    ("service.session", "metrics.counter", "counter incs during "
     "execution"),
    ("service.session", "metrics.timer", "timer observations at query "
     "end"),
    ("service.session", "config.registry", "late conf registration "
     "on first import of an engine module"),
    # admission's on_event callback is an opaque callable statically;
    # at runtime it is SqlService._post (registry + bus)
    ("service.admission", "service.records", "on_event -> "
     "SqlService._post -> get_query while holding the slot cv"),
    ("service.admission", "obs.bus", "on_event -> bus.post snapshot "
     "while holding the slot cv"),
    # pool._create constructs a session, whose default listeners
    # register on its (new) bus
    ("service.pool", "obs.bus", "SessionPool._create -> "
     "session.add_listener under the pool lock"),
    # the executor registers its cancel token while the session lease
    # is held (lifecycle.enter_query_scope from execute_batch)
    ("service.session", "execution.lifecycle", "executor registers "
     "the query's cancel token under the lease"),
    # admission/arbiter cv waits run lifecycle.checkpoint each wakeup,
    # which fires the cancel_point chaos seam (faults.plan counting)
    ("service.admission", "faults.plan", "queue-wait wakeups fire the "
     "cancel_point seam while holding the slot cv"),
    ("service.arbiter", "faults.plan", "lease-wait wakeups fire the "
     "cancel_point seam while holding the lease cv"),
    # the out-of-process UDF lane checks workers out while the query
    # runs under its session lease (execution/python_eval.py)
    ("service.session", "udf.pool", "worker checkout/checkin during "
     "UDF evaluation under the lease"),
    # status-store per-session feed: the bus delivers query start/end
    # synchronously on the worker thread holding the session lease
    ("service.session", "obs.status", "status-store feed folds "
     "query start/end attribution under the lease"),
    # flight recorder: same synchronous delivery, plus the executor's
    # crash-dump trigger runs inside the lease
    ("service.session", "obs.flightrec", "flight-recorder ring "
     "appends and crash dumps under the lease"),
    ("service.session", "metrics.histogram", "latency histogram "
     "observations at query end under the lease"),
    # pool._create wires the status-store feed while holding the pool
    # lock (SqlService._make_listener -> status_store.bind)
    ("service.pool", "obs.status", "session creation binds the "
     "status-store feed under the pool lock"),
    # registry.snapshot() serializes each histogram under its own leaf
    # lock while holding the instrument-map lock
    ("metrics.registry", "metrics.histogram", "MetricsRegistry."
     "snapshot reads histogram snapshots under the registry lock"),
)


# ---------------------------------------------------------------------------
# Lookup helpers
# ---------------------------------------------------------------------------

_BY_ID = {d.lock_id: d for d in LOCKS}


def lock_ids() -> Tuple[str, ...]:
    return tuple(d.lock_id for d in LOCKS)


def rank_of(lock_id: str) -> Optional[int]:
    d = _BY_ID.get(lock_id)
    return None if d is None else d.rank


def kind_of(lock_id: str) -> Optional[str]:
    d = _BY_ID.get(lock_id)
    return None if d is None else d.kind


def lock_id_for(relpath: str, cls: str, attr: str) -> Optional[str]:
    for d in LOCKS:
        if (d.relpath, d.cls, d.attr) == (relpath, cls, attr):
            return d.lock_id
    return None


def class_locks(relpath: str, cls: str) -> Dict[str, str]:
    """{lock attr name: lock_id} for one class (or module, cls='')."""
    return {d.attr: d.lock_id for d in LOCKS
            if d.relpath == relpath and d.cls == cls}

"""Jaxpr-level stage analyzer: abstract-eval the stage callable and
walk the equation graph.

The plan walk (`plan_analyzer`) predicts hazards from tree shape; this
half *confirms* what the stage actually lowers to, by tracing the same
callable the executor is about to jit (`jax.make_jaxpr` — abstract
evaluation only, no XLA compile, no device work) and scanning the
equations recursively (into pjit/scan/while/cond sub-jaxprs):

- collective primitives: `all_gather` under a mesh is full replication
  on the wire (the definitive form of the plan walk's
  MESH_FULL_REPLICATION prediction); `psum`/`pmax` are the stats
  channel and deliberately not findings.
- host callbacks (`pure_callback`/`io_callback`/...): every dispatch of
  the stage blocks on a host transition.
- int32 reduction accumulators while x64 is off: the silent-wrap shape
  the dtype-overflow category exists for, visible in the lowered ops.

Tracing costs one extra abstract trace per *unique stage key* — results
are memoized by the executor next to the XLA cost analyses, and
gated by `spark_tpu.sql.analysis.jaxpr` ('auto' traces only when an
observability output is configured or strict mode is on, mirroring the
xlaCost gate).
"""

from __future__ import annotations

from typing import Iterator, List

from .findings import Finding

#: collective primitive names that materialize full replication
_GATHER_PRIMS = ("all_gather",)

#: host-callback primitive names across jax versions
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "callback",
                   "debug_callback")

#: reduction primitives whose out-dtype is the accumulator dtype
_REDUCE_PRIMS = ("reduce_sum", "cumsum", "scatter-add", "segment_sum")


def _iter_eqns(jaxpr) -> Iterator:
    """Depth-first over every equation, descending into sub-jaxprs
    (pjit bodies, scan/while/cond branches, shard_map bodies) —
    duck-typed on `.eqns`/`.jaxpr`, so no jax.core version coupling."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    inner = getattr(v, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
        return
    if hasattr(v, "eqns"):
        yield v
        return
    if isinstance(v, (list, tuple)):
        for item in v:
            yield from _sub_jaxprs(item)


def trace_stage(fn, args):
    """Abstract-eval `fn(*args)` to a closed jaxpr (no compile). Raises
    whatever tracing raises — callers isolate."""
    import jax
    return jax.make_jaxpr(fn)(*args)


def analyze_jaxpr(closed_jaxpr, mesh_n: int = 1) -> List[Finding]:
    import jax
    import numpy as np
    x64 = bool(jax.config.jax_enable_x64)
    gathers = 0
    callbacks = set()
    i32_accums = 0
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in _GATHER_PRIMS:
            gathers += 1
        elif name in _CALLBACK_PRIMS:
            callbacks.add(name)
        elif not x64 and name in _REDUCE_PRIMS:
            for out in eqn.outvars:
                dt = getattr(getattr(out, "aval", None), "dtype", None)
                if dt is not None and np.dtype(dt) == np.dtype(np.int32):
                    i32_accums += 1
                    break
    out: List[Finding] = []
    if gathers and mesh_n > 1:
        out.append(Finding(
            "JAXPR_ALL_GATHER",
            f"stage lowers to {gathers} all_gather collective(s) across "
            f"the {mesh_n}-shard mesh: full replication confirmed in "
            f"the traced program",
            detail={"all_gather_eqns": gathers, "mesh_n": mesh_n}))
    if callbacks:
        out.append(Finding(
            "JAXPR_HOST_CALLBACK",
            f"stage contains host callback primitive(s) "
            f"{sorted(callbacks)}: every dispatch blocks on a "
            f"device->host transition",
            detail={"primitives": sorted(callbacks)}))
    if i32_accums:
        out.append(Finding(
            "JAXPR_I32_ACCUMULATOR",
            f"{i32_accums} reduction(s) accumulate into int32 with "
            f"jax_enable_x64 off: sums wrap at 2^31",
            detail={"reductions": i32_accums}))
    return out

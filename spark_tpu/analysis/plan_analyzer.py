"""Pre-compile physical-plan analyzer.

Runs after planning and before `_compile_stage` (the seat of Catalyst's
`CheckAnalysis` + Tungsten's fail-fast codegen checks): a pure tree walk
over the physical plan — no tracing, no device work — that turns the
hazards this engine previously discovered at runtime (or never) into
typed `Finding`s:

- **dtype-overflow**: SUM/AVG whose input-row bound x max value
  magnitude exceeds the int64 accumulator range. Magnitude bounds come
  from `expr.static_unsigned_bits` (pmod/literal shapes), integral
  widths, or decimal precision; *unbounded* 64-bit inputs are assumed
  in-range (the scaled-int64 representation is itself the cap —
  flagging every `sum(long)` would be pure noise).
- **host-sync**: plans that will execute through per-chunk host-driven
  loops (streaming aggregates past `streamingChunkRows`, deviceBudget
  spill reroutes, Python UDF round trips, mesh-side generate
  materialization) — each chunk pays a blocking device->host sync.
- **recompile**: static capacities baked into the stage-cache key
  (`describe()`) that are not bucket-aligned, so the key varies with
  exact input sizes and XLA recompiles per size instead of per bucket.
- **mesh**: exchanges that lower to full replication (all_gather) under
  `shard_map`.
- **x64**: 64-bit columns while `jax_enable_x64` is off — device arrays
  silently truncate to 32 bits.

The walk must never fail a query: callers wrap it, and per-node checks
swallow their own analysis errors.
"""

from __future__ import annotations

import math
from typing import List, Optional

from ..columnar import bucket_capacity
from ..plan import physical as P
from .. import types as T
from .findings import Finding

#: int64 accumulator magnitude bits (AccSpec np_dtype is int64; sums
#: wrap past 2^63)
_ACC_BITS = 63

#: decimal precisions above this already exceed int64 representation —
#: the engine's scaled-int64 column is the binding cap, not the
#: accumulator, so the analyzer has nothing tighter to say
_MAX_BOUNDED_DECIMAL_PRECISION = 18


def _node_loc(node: P.PhysicalPlan) -> str:
    tag = getattr(node, "op_tag", "") or getattr(node, "tag", "")
    name = type(node).__name__
    return f"{name}[{tag}]" if tag else name


def _estimate_rows(node: P.PhysicalPlan) -> Optional[int]:
    from ..plan.runtime_filter import estimate_rows_physical
    try:
        return estimate_rows_physical(node)
    except Exception:  # noqa: BLE001 — estimates are best-effort
        return None


def _value_bits(expr, schema) -> Optional[int]:
    """Static bound b with |values| < 2^b, or None (unbounded/unknown).
    Order matters: an expression-level bound (pmod/literal) beats the
    dtype width."""
    from ..expr import static_unsigned_bits
    w = static_unsigned_bits(expr)
    if w is not None:
        return min(w, 63)
    try:
        dt = expr.dtype(schema)
    except Exception:  # noqa: BLE001 — unresolvable: no bound
        return None
    if isinstance(dt, T.DecimalType):
        if dt.precision > _MAX_BOUNDED_DECIMAL_PRECISION:
            return None
        return max(1, math.ceil(dt.precision * math.log2(10)))
    if isinstance(dt, T.BooleanType):
        return 1
    if isinstance(dt, T.IntegralType):
        width = 8 * dt.np_dtype.itemsize - 1
        return width if width < 63 else None
    return None


def _find_transparent_scan(node: P.PhysicalPlan, name: str
                           ) -> Optional[P.ScanExec]:
    """The ScanExec that produces column `name` UNCHANGED below
    `node`, or None. Same discipline as the runtime-filter descent's
    `_keys_transparent`: name resolution alone is not enough — a
    Project aliasing a different expression onto the name, an
    ambiguous join, or an aggregate computing it means the scan's
    footer bounds do not bound the column's values here."""
    from ..expr import Alias, ColumnRef
    if isinstance(node, P.ScanExec):
        try:
            names = node.schema().names
        except Exception:  # noqa: BLE001
            return None
        return node if name in names else None
    if isinstance(node, (P.FilterExec, P.ExchangeExec, P.SortExec,
                         P.LimitExec, P.RuntimeFilterExec)):
        return _find_transparent_scan(node.children[0], name)
    if isinstance(node, P.ProjectExec):
        for e in node.exprs:
            if e.name() != name:
                continue
            base = e
            while isinstance(base, Alias):
                base = base.child
            if isinstance(base, ColumnRef) and base.name() == name:
                return _find_transparent_scan(node.children[0], name)
            return None
        return None
    if isinstance(node, P.JoinExec):
        try:
            in_left = name in node.left.schema().names
            in_right = name in node.right.schema().names
        except Exception:  # noqa: BLE001
            return None
        if in_left and in_right:
            return None  # ambiguous origin
        if in_left:
            return _find_transparent_scan(node.left, name)
        if in_right:
            return _find_transparent_scan(node.right, name)
    return None


def _footer_value_bits(expr, node: P.PhysicalPlan, conf
                       ) -> Optional[int]:
    """Magnitude bound from Parquet-footer column statistics: bits b
    with |values| < 2^b for a plain column reference whose scan-level
    min/max survived the descent. Tightens (or, for unbounded 64-bit
    inputs, establishes) the dtype-width bound — the carried ROADMAP
    lever."""
    from ..expr import Alias, ColumnRef
    if conf is None or not bool(conf.get(
            "spark_tpu.sql.stats.parquetFooter")):
        return None
    base = expr
    while isinstance(base, Alias):
        base = base.child
    if not isinstance(base, ColumnRef):
        return None
    name = base.name()
    scan = _find_transparent_scan(node.children[0], name)
    if scan is None:
        return None
    try:
        stats = (scan.source.column_stats() or {}).get(name)
        dt = scan.schema().field(name).dtype
    except Exception:  # noqa: BLE001 — stats are advisory
        return None
    if stats is None:
        return None
    import decimal
    mags = []
    for v in (stats.get("min"), stats.get("max")):
        if isinstance(v, bool) or not isinstance(
                v, (int, decimal.Decimal)):
            return None
        if isinstance(dt, T.DecimalType):
            v = int(abs(decimal.Decimal(v)).scaleb(dt.scale))
        else:
            v = abs(int(v))
        mags.append(v)
    return max(1, int(max(mags)).bit_length())


def _check_agg_overflow(node: P.HashAggregateExec, out: List[Finding],
                        conf=None) -> None:
    """SUM/AVG accumulators are int64 for integral/decimal inputs
    (expr_agg.Sum.accumulators); a bound of rows x 2^value_bits past
    2^63 means the total can wrap with no error raised anywhere.
    Magnitude bounds take the TIGHTEST of the expression/dtype bound
    and the Parquet-footer min/max bound."""
    from ..expr_agg import Avg, Sum
    if node.mode == "final":
        return  # the partial stage below already carries the bound
    rows = _estimate_rows(node.children[0])
    if rows is None or rows <= 0:
        return
    rows_bits = max(1, int(rows - 1).bit_length())
    base = node._base_schema()
    for a in node.agg_exprs:
        f = a.func
        if not isinstance(f, (Sum, Avg)) or f.child is None:
            continue
        try:
            dt = f.child.dtype(base)
        except Exception:  # noqa: BLE001
            continue
        if isinstance(dt, T.FloatType) and rows >= (1 << 24):
            out.append(Finding(
                "SUM_F32_INPUT",
                f"{a.out_name}: summing ~{rows:,} float32 values; the "
                f"inputs carry 24-bit mantissas, so the accumulated "
                f"total inherits their rounding error",
                op=_node_loc(node),
                detail={"rows_bound": int(rows)}))
            continue
        if not isinstance(dt, (T.IntegralType, T.DecimalType)):
            continue
        bits = _value_bits(f.child, base)
        footer_bits = _footer_value_bits(f.child, node, conf)
        if footer_bits is not None:
            bits = footer_bits if bits is None else min(bits, footer_bits)
        if bits is None:
            continue
        if rows_bits + bits > _ACC_BITS:
            out.append(Finding(
                "SUM_I64_OVERFLOW",
                f"{a.out_name}: up to ~{rows:,} rows x |value| < "
                f"2^{bits} needs {rows_bits + bits} bits; the int64 "
                f"accumulator holds {_ACC_BITS} — the sum can wrap "
                f"silently",
                op=_node_loc(node),
                detail={"rows_bound": int(rows), "value_bits": int(bits),
                        "required_bits": int(rows_bits + bits),
                        "acc_bits": _ACC_BITS, "agg": repr(f)}))


#: scan bound above which a row-at-a-time UDF's per-row interpreter
#: crossings dominate the stage (the @pandas_udf suggestion threshold)
_UDF_SCALAR_LARGE_ROWS = 1 << 16


def _check_udf_roundtrip(root: P.PhysicalPlan, conf,
                         out: List[Finding]) -> None:
    """UDF_HOST_ROUNDTRIP with a batch-count/bytes prediction derived
    from scan estimates (graded by history.prediction_report against
    the observed `udf_batches`/`udf_rows` counters), plus an info note
    per scalar UDF sitting over a large scan."""
    from ..execution.python_eval import node_udfs
    max_rec = int(conf.get(
        "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"))
    rows_total = 0
    bytes_total = 0
    udf_nodes = 0
    scalar_large: List[tuple] = []
    seen = set()

    def walk(node):
        nonlocal rows_total, bytes_total, udf_nodes
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        udfs = node_udfs(node)
        if not udfs:
            return
        udf_nodes += 1
        src = node.children[0] if node.children else node
        rows = _estimate_rows(src)
        if rows is None or rows <= 0:
            return
        rows_total += rows
        try:
            width = 8 * max(1, len(src.schema().fields))
        except Exception:  # noqa: BLE001 — width is best-effort
            width = 8
        bytes_total += rows * width
        for u in udfs:
            if not u.vectorized and rows >= _UDF_SCALAR_LARGE_ROWS:
                scalar_large.append((u.udf_name, int(rows), node))

    walk(root)
    if not udf_nodes:
        return
    detail = {"max_records_per_batch": max_rec}
    msg = ("plan contains Python UDFs: the stage splits around a "
           "device->host->device round trip per batch")
    if rows_total:
        detail.update(
            rows_bound=int(rows_total),
            batches_bound=int(-(-rows_total // max_rec)),
            bytes_bound=int(bytes_total))
        msg += (f" (~{detail['batches_bound']:,} batches of <= "
                f"{max_rec:,} rows, ~{rows_total:,} rows round-tripped)")
    out.append(Finding("UDF_HOST_ROUNDTRIP", msg,
                       op=_node_loc(root), detail=detail))
    for name, rows, node in scalar_large:
        out.append(Finding(
            "UDF_SCALAR_LARGE_INPUT",
            f"{name}: scalar UDF over ~{rows:,} input rows crosses "
            f"the interpreter once per row; @pandas_udf evaluates the "
            f"same logic once per <= {max_rec:,}-row Arrow batch",
            op=_node_loc(node),
            detail={"rows_bound": int(rows), "udf": name}))


def _check_host_sync(root: P.PhysicalPlan, conf,
                     mesh_n: int, out: List[Finding]) -> None:
    _check_udf_roundtrip(root, conf, out)

    chunk_rows = int(conf.get(
        "spark_tpu.sql.execution.streamingChunkRows"))
    budget = int(conf.get("spark_tpu.sql.memory.deviceBudget"))
    seen = set()  # runtime-filter creation chains DAG-share their
    # leaves with the join build side: analyze each node once

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if isinstance(node, P.GenerateExec) and mesh_n > 1:
            out.append(Finding(
                "GENERATE_MESH_MATERIALIZE",
                "explode under a mesh executes its subtree single-device "
                "(host-materialized) before sharding the flat result",
                op=_node_loc(node)))
        if isinstance(node, P.HashAggregateExec) \
                and node.mode in ("complete", "partial"):
            from ..execution.streaming_agg import find_streamable_chain
            found = find_streamable_chain(node)
            if found is None:
                return
            _chain, leaf = found
            rows = _estimate_rows(leaf)
            if rows is not None and rows > chunk_rows > 0:
                n_chunks = -(-rows // chunk_rows)
                out.append(Finding(
                    "STREAMING_HOST_SYNC",
                    f"~{rows:,} input rows stream through the aggregate "
                    f"in ~{n_chunks} chunks of {chunk_rows:,}, each with "
                    f"a blocking device->host stats sync",
                    op=_node_loc(node),
                    detail={"rows_bound": int(rows),
                            "chunks": int(n_chunks)}))
        if isinstance(node, P.ScanExec) and budget > 0:
            from ..io.device_cache import estimated_scan_bytes
            try:
                est_b = estimated_scan_bytes(node)
            except Exception:  # noqa: BLE001
                est_b = None
            if est_b is not None and est_b > budget:
                out.append(Finding(
                    "SPILL_HOST_SYNC",
                    f"estimated scan footprint ~{est_b:,} bytes exceeds "
                    f"memory.deviceBudget={budget:,}: execution reroutes "
                    f"through the host-spill chunked path",
                    op=_node_loc(node),
                    detail={"estimated_bytes": int(est_b),
                            "budget_bytes": int(budget)}))

    walk(root)


def _check_recompile(root: P.PhysicalPlan, conf,
                     out: List[Finding]) -> None:
    """Every capacity below appears verbatim in `simple_string()` and
    hence in the stage-cache key: an unbucketed value means two inputs
    differing by one row compile two distinct XLA programs.

    Alignment is checked against `bucket_capacity`'s DEFAULT growth —
    the one every producer in the engine actually pads with (planner,
    AQE cap growth, runtime-filter sizing all call it bare). The
    `bucketGrowth` conf is deliberately not consulted here: no producer
    threads it through yet, so validating against a non-default value
    would flag every engine-produced power-of-two capacity."""

    def flag(node, kind: str, value: int) -> None:
        if value is None:
            return
        if bucket_capacity(int(value)) != int(value):
            out.append(Finding(
                "UNBUCKETED_CAPACITY",
                f"{kind}={value:,} is not bucket-aligned: the "
                f"stage-cache key varies with exact input sizes — "
                f"expect a recompile per size instead of per bucket",
                op=_node_loc(node),
                detail={"kind": kind, "value": int(value),
                        "bucketed": bucket_capacity(int(value))}))

    seen = set()

    def walk(node):
        if id(node) in seen:  # runtime-filter creation chains DAG-share
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if isinstance(node, P.JoinExec):
            flag(node, "join.out_cap", node.out_cap)
        elif isinstance(node, P.ExchangeExec):
            flag(node, "exchange.block_cap", node.block_cap)
        elif isinstance(node, P.HashAggregateExec):
            flag(node, "aggregate.est_groups", node.est_groups)
        elif isinstance(node, P.RuntimeFilterExec):
            flag(node, "runtime_filter.est_items", node.est_items)

    walk(root)


def _check_hash_join(root: P.PhysicalPlan, conf,
                     out: List[Finding]) -> None:
    """Predict degraded hash-kernel choices (JOIN_HASH_TABLE_PRESSURE):
    for each join the conf would run on the hash kernel, size the
    open-addressing table from the ESTIMATED (bucketed) build capacity
    — exactly `hash_join.table_slots` — and warn when the
    hashMaxTableSlots clamp forces the sort fallback (load factor
    > 0.7) or the table's slot bytes exceed the device HBM budget.
    Mirrors `resolve_kernel`, so `explain(analysis=True)` shows the
    fallback BEFORE a trace silently takes it."""
    from ..execution import hash_join as HJ
    mode = str(conf.get(HJ.KERNEL_MODE_KEY))
    if mode == "sort":
        return
    budget = int(conf.get("spark_tpu.sql.memory.deviceBudget")) \
        or int(conf.get("spark_tpu.service.hbmBudget"))
    seen = set()

    def walk(node):
        if id(node) in seen:  # runtime-filter creation chains DAG-share
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if not isinstance(node, P.JoinExec):
            return
        build_rows = _estimate_rows(node.right)
        probe_rows = _estimate_rows(node.left)
        if build_rows is None:
            return
        if node.hash_fallback is False:
            return  # already pinned to sort by the AQE loop
        build_cap = bucket_capacity(max(int(build_rows), 8))
        probe_cap = bucket_capacity(max(int(probe_rows or 0), 8))
        # the EXACT runtime decision procedure: heuristic sort choices
        # ('small-probe'/'ratio') are not degradations, only the clamp
        # fallback and HBM pressure on a chosen hash path are
        kernel, reason = HJ.kernel_choice(conf, probe_cap, build_cap)
        if kernel == "sort" and reason != "clamp":
            return
        slots = HJ.table_slots(build_cap, conf)
        table_bytes = slots * HJ.SLOT_BYTES
        if reason == "clamp":
            out.append(Finding(
                "JOIN_HASH_TABLE_PRESSURE",
                f"estimated build capacity {build_cap:,} under the "
                f"hashMaxTableSlots clamp ({slots:,} slots) pushes the "
                f"load factor past 0.7: this join silently falls back "
                f"to the sort kernel",
                op=_node_loc(node),
                detail={"build_cap": int(build_cap),
                        "slots": int(slots), "fallback": "sort"}))
        elif budget > 0 and table_bytes > budget:
            out.append(Finding(
                "JOIN_HASH_TABLE_PRESSURE",
                f"hash table for this join needs {slots:,} slots "
                f"(~{table_bytes:,} bytes) against a device budget of "
                f"{budget:,}: the build pressures the HBM lease",
                op=_node_loc(node),
                detail={"slots": int(slots),
                        "table_bytes": int(table_bytes),
                        "budget_bytes": int(budget)}))

    walk(root)


def _check_mesh(root: P.PhysicalPlan, mesh_n: int,
                out: List[Finding]) -> None:
    if mesh_n <= 1:
        return
    seen = set()  # DAG-shared creation chains: one visit per node

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if not isinstance(node, P.ExchangeExec):
            return
        part = node.partitioning
        rows = _estimate_rows(node.children[0])
        width = 8 * max(1, len(node.schema().fields))
        est_b = rows * width * mesh_n if rows is not None else None
        detail = {"mesh_n": mesh_n}
        if est_b is not None:
            detail["replicated_bytes_bound"] = int(est_b)
        if isinstance(part, P.Replicated):
            out.append(Finding(
                "MESH_FULL_REPLICATION",
                f"broadcast exchange all-gathers its child onto all "
                f"{mesh_n} shards"
                + (f" (~{est_b:,} bytes total)" if est_b else ""),
                op=_node_loc(node), detail=detail))
        elif isinstance(part, P.SinglePartition):
            out.append(Finding(
                "MESH_GATHER_RESULT",
                f"single-partition exchange gathers all rows onto every "
                f"shard (global sort/aggregate collection point)",
                op=_node_loc(node), detail=detail))

    walk(root)


def _check_x64(root: P.PhysicalPlan, out: List[Finding]) -> None:
    import jax
    if jax.config.jax_enable_x64:
        return
    wide = {}

    def walk(node):
        for c in node.children:
            walk(c)
        try:
            fields = node.schema().fields
        except Exception:  # noqa: BLE001 — schema errors surface later
            return
        for f in fields:
            np_dtype = getattr(f.dtype, "np_dtype", None)
            if np_dtype is not None and np_dtype.itemsize >= 8:
                wide.setdefault(f.name, repr(f.dtype))

    walk(root)
    if wide:
        cols = ", ".join(f"{n}:{d}" for n, d in sorted(wide.items())[:8])
        out.append(Finding(
            "X64_TRUNCATION",
            f"jax_enable_x64 is off but the plan carries 64-bit "
            f"columns ({cols}{', ...' if len(wide) > 8 else ''}): device "
            f"arrays will silently truncate to 32 bits",
            op=_node_loc(root),
            detail={"columns": sorted(wide)}))


def analyze_plan(root: P.PhysicalPlan, conf,
                 mesh_n: int = 1) -> List[Finding]:
    """All plan-level findings for one physical tree. Pure host-side
    walk (microseconds); individual checks isolate their own failures
    so a broken estimator can never fail the query."""
    out: List[Finding] = []
    checks = (
        lambda: _walk_aggregates(root, out, conf),
        lambda: _check_host_sync(root, conf, mesh_n, out),
        lambda: _check_recompile(root, conf, out),
        lambda: _check_hash_join(root, conf, out),
        lambda: _check_mesh(root, mesh_n, out),
        lambda: _check_x64(root, out),
    )
    for check in checks:
        try:
            check()
        except Exception as e:  # noqa: BLE001 — analysis is advisory
            import warnings
            warnings.warn(f"plan analysis check failed (skipped): "
                          f"{type(e).__name__}: {e}")
    return out


def _walk_aggregates(root: P.PhysicalPlan, out: List[Finding],
                     conf=None) -> None:
    seen = set()

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if isinstance(node, P.HashAggregateExec):
            _check_agg_overflow(node, out, conf)

    walk(root)

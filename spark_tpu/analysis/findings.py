"""Typed findings for the pre-compile static analyzer.

The Catalyst analyzer raises `AnalysisException` for unresolvable
plans; this engine's plans always resolve (schema checking happens in
`executor.analyzed`), but a *resolvable* plan can still be hazardous on
a TPU: an int sum can wrap its 64-bit accumulator at scale, a streaming
aggregate pays a blocking host sync per chunk, an unbucketed static
capacity in the stage-cache key recompiles per input size, a broadcast
under `shard_map` all-gathers a full table, and a 64-bit column
silently truncates when JAX x64 is off. Each of those is a typed
`Finding` with a stable code, produced by `plan_analyzer` (tree walk)
and `jaxpr_analyzer` (abstract-eval walk) and surfaced through the
listener bus, the event log, and `explain(analysis=True)`.

Severity discipline:

- ``error``: the query is likely to return WRONG RESULTS or fail
  (overflow wrap, x64 truncation). `spark_tpu.sql.analysis.strict`
  turns these into a pre-compile `AnalysisFindingError`.
- ``warn``: correct but hazardous for performance/stability (host-sync
  loops, recompile churn, full replication).
- ``info``: worth recording, no action expected.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: category slugs (one per analyzer concern; the acceptance bar is >=1
#: distinct finding code per category on seeded-violation plans)
CAT_OVERFLOW = "dtype-overflow"
CAT_HOST_SYNC = "host-sync"
CAT_RECOMPILE = "recompile"
CAT_MESH = "mesh"
CAT_X64 = "x64"
CAT_KERNEL = "kernel"
CAT_PLAN = "plan-integrity"

CATEGORIES = (CAT_OVERFLOW, CAT_HOST_SYNC, CAT_RECOMPILE, CAT_MESH,
              CAT_X64, CAT_KERNEL, CAT_PLAN)

#: finding code -> (category, severity, one-line doc). The registry is
#: closed on purpose: an ad-hoc code would dodge the README table and
#: any consumer keying on codes (mirrors METRIC_PREFIXES discipline).
FINDING_CODES: Dict[str, tuple] = {
    "SUM_I64_OVERFLOW": (
        CAT_OVERFLOW, "error",
        "capacity x max-magnitude of a SUM/AVG input exceeds the int64 "
        "accumulator range: the sum can wrap silently"),
    "SUM_F32_INPUT": (
        CAT_OVERFLOW, "info",
        "SUM/AVG over float32 input data: each element carries only a "
        "24-bit mantissa, so the (float64-accumulated) total inherits "
        "float32 input error"),
    "STREAMING_HOST_SYNC": (
        CAT_HOST_SYNC, "warn",
        "scan exceeds streamingChunkRows: the aggregate streams in "
        "host-driven chunks with a blocking device->host sync per chunk"),
    "SPILL_HOST_SYNC": (
        CAT_HOST_SYNC, "warn",
        "estimated scan footprint exceeds memory.deviceBudget: execution "
        "reroutes through the host-spill chunked path (device_get per "
        "chunk)"),
    "UDF_HOST_ROUNDTRIP": (
        CAT_HOST_SYNC, "warn",
        "Python UDF in the plan: the stage splits around a "
        "device->host->device round trip per batch"),
    "UDF_SCALAR_LARGE_INPUT": (
        CAT_HOST_SYNC, "info",
        "a scalar (row-at-a-time) Python UDF sits over a large scan: "
        "every row crosses the interpreter individually — @pandas_udf "
        "runs the same logic vectorized over whole Arrow batches"),
    "GENERATE_MESH_MATERIALIZE": (
        CAT_HOST_SYNC, "warn",
        "explode/generate under a mesh materializes its subtree "
        "single-device on the host before sharding the flat result"),
    "JAXPR_HOST_CALLBACK": (
        CAT_HOST_SYNC, "warn",
        "the traced stage contains a host callback primitive: every "
        "dispatch blocks on a device->host transition"),
    "UNBUCKETED_CAPACITY": (
        CAT_RECOMPILE, "warn",
        "a static capacity baked into the stage-cache key is not "
        "bucket-aligned (columnar.bucket_capacity): the key varies with "
        "exact input sizes and recompiles per size instead of per "
        "bucket"),
    "MESH_FULL_REPLICATION": (
        CAT_MESH, "warn",
        "a broadcast exchange under shard_map all-gathers a full "
        "relation onto every shard (n_shards x its bytes of ICI traffic "
        "and HBM)"),
    "MESH_GATHER_RESULT": (
        CAT_MESH, "info",
        "a single-partition exchange under shard_map gathers all rows "
        "onto every shard (expected for global sorts/aggregates; "
        "hazardous when the gathered relation is large)"),
    "JAXPR_ALL_GATHER": (
        CAT_MESH, "warn",
        "the traced stage lowers to all_gather collectives under "
        "shard_map (full replication confirmed in the jaxpr)"),
    "X64_TRUNCATION": (
        CAT_X64, "error",
        "a 64-bit column (long/double/timestamp/decimal) is used while "
        "JAX x64 is disabled: device arrays silently truncate to 32 "
        "bits"),
    "JOIN_HASH_TABLE_PRESSURE": (
        CAT_KERNEL, "warn",
        "a join the conf would run on the hash kernel degrades: the "
        "hashMaxTableSlots-clamped table either forces the sort "
        "fallback (load factor > 0.7) or its slot bytes exceed the "
        "device HBM budget — the kernel choice silently falls back or "
        "pressures the lease"),
    "JAXPR_I32_ACCUMULATOR": (
        CAT_X64, "warn",
        "the traced stage reduces into an int32 accumulator with JAX "
        "x64 disabled: sums wrap at 2^31"),
    "PLAN_INTEGRITY": (
        CAT_PLAN, "error",
        "an optimizer rule application broke a plan invariant "
        "(unresolvable/ambiguous column reference, undeclared output-"
        "schema change, duplicate output names, incoherent aggregate, "
        "incompatible join-key dtypes, or a nondeterministic batch "
        "rewrite) — the rewritten plan can return wrong results; "
        "produced by analysis/plan_integrity.py under "
        "spark_tpu.sql.planChangeValidation=lite (full raises "
        "PlanIntegrityError instead)"),
}


@dataclass
class Finding:
    """One typed analyzer finding, event-log serializable."""

    code: str
    message: str
    op: str = ""  # op_tag / node identity the finding anchors to
    detail: Dict = field(default_factory=dict)

    def __post_init__(self):
        if self.code not in FINDING_CODES:
            raise ValueError(
                f"unknown finding code {self.code!r}; register it in "
                f"analysis.findings.FINDING_CODES")

    @property
    def category(self) -> str:
        return FINDING_CODES[self.code][0]

    @property
    def severity(self) -> str:
        return FINDING_CODES[self.code][1]

    def to_dict(self) -> Dict:
        d = {"code": self.code, "category": self.category,
             "severity": self.severity, "message": self.message}
        if self.op:
            d["op"] = self.op
        if self.detail:
            d["detail"] = self.detail
        return d

    def render(self) -> str:
        loc = f" at {self.op}" if self.op else ""
        return f"[{self.severity}] {self.code} ({self.category}){loc}: " \
               f"{self.message}"


class AnalysisFindingError(RuntimeError):
    """Raised pre-compile under `spark_tpu.sql.analysis.strict` when the
    analyzer produced error-severity findings. Carries the full list so
    callers (and tests) can inspect codes structurally."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)
        errors = [f for f in self.findings if f.severity == "error"]
        lines = "\n".join("  " + f.render() for f in errors)
        super().__init__(
            f"static analysis failed (analysis.strict=true): "
            f"{len(errors)} error finding(s) before compile:\n{lines}")


def errors_of(findings: List[Finding]) -> List[Finding]:
    return [f for f in findings if f.severity == "error"]

"""Python UDFs: scalar (row-at-a-time), pandas (vectorized), and
grouped-map user functions.

The reference runs Python UDFs in forked CPython workers fed Arrow
batches over sockets (`ArrowEvalPythonExec.scala:1`,
`core/.../api/python/PythonRunner.scala:84`, `python/pyspark/worker.py:504`).
This engine IS Python, so the whole IPC stack collapses to a host
round-trip: the executor materializes the UDF's input subtree (a stage,
like a QueryStageExec), pulls the referenced columns to host in one
batched transfer, evaluates the function, and splices the result back as
a device column. Everything around the UDF stays jitted; the UDF itself
is the host island — exactly the stage cut the reference makes, minus
the sockets.

NULL semantics follow the reference's BatchEvalPythonExec: scalar UDFs
receive Python ``None`` for NULL inputs and may return ``None`` for a
NULL result; pandas UDFs receive ``pd.Series`` with NaN/None holes.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import pandas as pd
import pyarrow as pa

from . import types as T
from .expr import AnalysisError, Expression, _wrap


def _parse_return_type(rt) -> T.DataType:
    if isinstance(rt, T.DataType):
        return rt
    names = {
        "long": T.LONG, "bigint": T.LONG, "int": T.INT, "integer": T.INT,
        "double": T.DOUBLE, "float": T.FLOAT, "string": T.STRING,
        "boolean": T.BOOLEAN, "bool": T.BOOLEAN, "date": T.DATE,
    }
    key = str(rt).strip().lower()
    if key in names:
        return names[key]
    raise AnalysisError(f"unsupported UDF return type {rt!r}")


class PythonUDF(Expression):
    """A user function call site. Never evaluates inside a trace — the
    executor's ExtractPythonUDFs pass (execution/python_eval.py) cuts
    the plan at this expression and evaluates it on host (the
    `ExtractPythonUDFs.scala` seam)."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 args: Sequence, name: Optional[str] = None,
                 vectorized: bool = False):
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(_wrap(a) for a in args)
        self.udf_name = name or getattr(fn, "__name__", "udf")
        self.vectorized = vectorized

    def dtype(self, schema):
        return self.return_type

    def nullable(self, schema):
        return True

    def eval(self, batch):
        raise AnalysisError(
            f"python UDF {self.udf_name!r} reached expression evaluation; "
            "UDFs are evaluated host-side by the executor's "
            "ExtractPythonUDFs pass")

    def name(self):
        return f"{self.udf_name}({', '.join(c.name() for c in self.children)})"

    def __repr__(self):
        return f"{self.udf_name}({', '.join(map(repr, self.children))})"


class UserDefinedFunction:
    """The object `F.udf(...)` returns: call it with columns to build a
    PythonUDF expression (pyspark's UserDefinedFunction surface)."""

    def __init__(self, fn: Callable, return_type, name=None,
                 vectorized=False):
        self.fn = fn
        self.return_type = _parse_return_type(return_type)
        self._name = name or getattr(fn, "__name__", "udf")
        self.vectorized = vectorized

    def __call__(self, *cols):
        return PythonUDF(self.fn, self.return_type, cols,
                         name=self._name, vectorized=self.vectorized)


def udf(f=None, returnType=T.DOUBLE):
    """``udf(lambda x: ..., "long")`` or ``@udf(returnType="long")``."""
    if f is None or isinstance(f, (str, T.DataType)):
        rt = returnType if f is None else f
        return lambda fn: UserDefinedFunction(fn, rt)
    return UserDefinedFunction(f, returnType)


def pandas_udf(f=None, returnType=T.DOUBLE):
    """Vectorized UDF: the function receives/returns ``pd.Series``
    (the reference's SQL_SCALAR_PANDAS_UDF over Arrow batches)."""
    if f is None or isinstance(f, (str, T.DataType)):
        rt = returnType if f is None else f
        return lambda fn: UserDefinedFunction(fn, rt, vectorized=True)
    return UserDefinedFunction(f, returnType, vectorized=True)


class UDFRegistration:
    """`session.udf.register(name, fn, returnType)` — makes the function
    callable from SQL (the reference's UDFRegistration.scala)."""

    def __init__(self, session):
        self._session = session
        self._fns = {}

    def register(self, name: str, fn, returnType=T.DOUBLE):
        if isinstance(fn, UserDefinedFunction):
            u = UserDefinedFunction(fn.fn, fn.return_type, name=name,
                                    vectorized=fn.vectorized)
        else:
            u = UserDefinedFunction(fn, returnType, name=name)
        self._fns[name.lower()] = u
        return u

    def lookup(self, name: str) -> Optional[UserDefinedFunction]:
        return self._fns.get(name.lower())


# ---------------------------------------------------------------------------
# Host evaluation (the worker.py:504 loop, minus the socket)
# ---------------------------------------------------------------------------

def evaluate_udf(node: PythonUDF, arg_arrays, arg_valids, n_rows: int):
    """Evaluate over host numpy/arrow arg columns ->
    (values list | np array, validity np array)."""
    if node.vectorized:
        series = []
        for a, v in zip(arg_arrays, arg_valids):
            s = pd.Series(a)
            if v is not None:
                s = s.where(pd.Series(v))
            series.append(s)
        out = node.fn(*series)
        if not isinstance(out, pd.Series):
            out = pd.Series(out)
        if len(out) != n_rows:
            raise RuntimeError(
                f"pandas UDF {node.udf_name!r} returned {len(out)} rows "
                f"for {n_rows} input rows")
        valid = ~out.isna().to_numpy()
        return out, valid
    results = []
    valid = np.ones(n_rows, dtype=bool)
    for i in range(n_rows):
        args = []
        for a, v in zip(arg_arrays, arg_valids):
            if v is not None and not v[i]:
                args.append(None)
            else:
                x = a[i]
                args.append(x.item() if isinstance(x, np.generic) else x)
        r = node.fn(*args)
        if r is None:
            valid[i] = False
            results.append(None)
        else:
            results.append(r)
    return results, valid


def result_to_arrow(node: PythonUDF, values, valid) -> pa.Array:
    """UDF python results -> typed arrow array (NULLs where invalid)."""
    rt = node.return_type
    if isinstance(values, pd.Series):
        values = values.to_numpy(dtype=object, na_value=None)
    cleaned = [None if not v else x for x, v in zip(values, valid)]
    if isinstance(rt, T.StringType):
        return pa.array([None if c is None else str(c) for c in cleaned],
                        type=pa.string())
    if isinstance(rt, T.DateType):
        return pa.array(cleaned, type=pa.date32())
    arrow_t = {
        np.dtype(np.int64): pa.int64(), np.dtype(np.int32): pa.int32(),
        np.dtype(np.float64): pa.float64(),
        np.dtype(np.float32): pa.float32(),
        np.dtype(np.bool_): pa.bool_(),
    }[np.dtype(rt.np_dtype)]
    return pa.array(cleaned, type=arrow_t)

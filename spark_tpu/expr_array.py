"""Array (complex-type) expressions: array(), size, array_contains,
element_at, explode.

Reference: `sql/catalyst/.../expressions/collectionOperations.scala` +
`complexTypeCreator.scala`, re-designed for the offsets-encoded device
layout (columnar.Column: flattened elements + int32 offsets — the Arrow
List layout instead of `UnsafeArrayData.java:1`). Every operation is a
whole-column vectorized pass; per-row element slices resolve through
offsets arithmetic and segment gathers, never per-row loops.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from . import types as T
from .expr import (AnalysisError, Expression, Literal, Vec, _and_valid,
                   _wrap, cast_vec)


def _value_segments(offsets, n_values: int):
    """For each flattened value slot, the row index owning it (cap for
    the dead tail past the last offset)."""
    iota = jnp.arange(n_values, dtype=jnp.int32)
    return jnp.searchsorted(offsets, iota, side="right") - 1


class MakeArray(Expression):
    """array(e1, e2, ...): each row's array is the N evaluated scalars
    (complexTypeCreator.scala CreateArray)."""

    def __init__(self, *children):
        if not children:
            raise AnalysisError("array() needs at least one element")
        self.children = tuple(_wrap(c) for c in children)

    def dtype(self, schema):
        dts = [c.dtype(schema) for c in self.children]
        out = dts[0]
        for dt in dts[1:]:
            out = T.common_type(out, dt)
        return T.ArrayType(out)

    def nullable(self, schema):
        return False

    def eval(self, batch):
        out_t = self.dtype(batch.schema())
        elem_t = out_t.element
        vs = [cast_vec(c.eval(batch), elem_t) for c in self.children]
        if any(v.dictionary is not None for v in vs):
            raise AnalysisError(
                "array() over string columns is not supported (per-"
                "column dictionaries have no shared encoding)")
        cap = batch.capacity
        n = len(vs)
        data = jnp.stack([v.data for v in vs], axis=1).reshape(-1)
        valids = [v.validity if v.validity is not None
                  else jnp.ones((cap,), jnp.bool_) for v in vs]
        if all(v.validity is None for v in vs):
            ev = None
        else:
            ev = jnp.stack(valids, axis=1).reshape(-1)
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * n)
        return Vec(data, out_t, None, None, offsets=offsets,
                   elem_validity=ev)

    def name(self):
        return f"array({', '.join(c.name() for c in self.children)})"

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class Size(Expression):
    """size(arr): element count per row; NULL input -> -1 (the
    reference's legacy sizeOfNull=true default)."""

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.INT

    def nullable(self, schema):
        return False

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.offsets is None:
            raise AnalysisError(f"size() needs an array, got {v.dtype!r}")
        sizes = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        if v.validity is not None:
            sizes = jnp.where(v.validity, sizes, jnp.int32(-1))
        return Vec(sizes, T.INT, None)

    def __repr__(self):
        return f"size({self.children[0]!r})"


class ArrayContains(Expression):
    """array_contains(arr, value): NULL row -> NULL; contains-null
    semantics follow the reference (no three-valued fallback: a missing
    match with null elements present yields NULL)."""

    def __init__(self, child, value):
        self.children = (_wrap(child), _wrap(value))

    def dtype(self, schema):
        return T.BOOLEAN

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.offsets is None:
            raise AnalysisError("array_contains() needs an array")
        lit = self.children[1]
        if not isinstance(lit, Literal):
            raise AnalysisError(
                "array_contains() requires a literal search value")
        elem_t = v.dtype.element
        if isinstance(elem_t, T.StringType):
            if v.dictionary is None:
                raise AnalysisError("string array without dictionary")
            import pyarrow.compute as pc
            idx = pc.index_in(lit.value, value_set=v.dictionary).as_py()
            needle = jnp.int32(-1 if idx is None else idx)
        else:
            needle = jnp.asarray(lit.value, v.data.dtype)
        nvals = v.data.shape[0]
        seg = _value_segments(v.offsets, nvals)
        hit = v.data == needle
        has_null_elem = jnp.zeros((batch.capacity,), jnp.bool_)
        if v.elem_validity is not None:
            hit = hit & v.elem_validity
            has_null_elem = jnp.zeros((batch.capacity + 1,), jnp.bool_) \
                .at[jnp.clip(seg, 0, batch.capacity)].max(
                    ~v.elem_validity)[:batch.capacity]
        found = jnp.zeros((batch.capacity + 1,), jnp.bool_).at[
            jnp.clip(seg, 0, batch.capacity)].max(hit)[:batch.capacity]
        # NULL when not found but a NULL element exists (reference
        # ArrayContains three-valued logic)
        validity = ~(~found & has_null_elem)
        validity = _and_valid(v.validity, validity)
        return Vec(found, T.BOOLEAN, validity)

    def __repr__(self):
        return (f"array_contains({self.children[0]!r}, "
                f"{self.children[1]!r})")


class ElementAt(Expression):
    """element_at(arr, i): 1-based; negative indexes from the end;
    out-of-bounds -> NULL (non-ANSI reference behavior)."""

    def __init__(self, child, index):
        self.children = (_wrap(child), _wrap(index))

    def dtype(self, schema):
        dt = self.children[0].dtype(schema)
        if not isinstance(dt, T.ArrayType):
            raise AnalysisError("element_at() needs an array")
        return dt.element

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.offsets is None:
            raise AnalysisError("element_at() needs an array")
        iv = cast_vec(self.children[1].eval(batch), T.INT)
        idx = iv.data
        if np.ndim(idx) == 0:
            idx = jnp.broadcast_to(idx, (batch.capacity,))
        starts = v.offsets[:-1]
        lens = v.offsets[1:] - starts
        pos = jnp.where(idx > 0, idx - 1, lens + idx)  # 1-based / from-end
        ok = (pos >= 0) & (pos < lens) & (idx != 0)
        slot = jnp.clip(starts + pos, 0, max(v.data.shape[0] - 1, 0))
        data = jnp.take(v.data, slot)
        validity = ok
        if v.elem_validity is not None:
            validity = validity & jnp.take(v.elem_validity, slot)
        validity = _and_valid(v.validity, validity)
        validity = _and_valid(iv.validity, validity)
        return Vec(data, self.dtype(batch.schema()), validity,
                   v.dictionary)

    def __repr__(self):
        return f"element_at({self.children[0]!r}, {self.children[1]!r})"


class Explode(Expression):
    """Marker: one output row per array element. Never evaluates as a
    column expression — the select paths extract it into a Generate
    plan node (reference: GenerateExec.scala:1 / ExtractGenerator)."""

    def __init__(self, child, outer: bool = False):
        self.children = (_wrap(child),)
        self.outer = outer

    def dtype(self, schema):
        dt = self.children[0].dtype(schema)
        if not isinstance(dt, T.ArrayType):
            raise AnalysisError(f"explode() needs an array, got {dt!r}")
        return dt.element

    def eval(self, batch):
        raise AnalysisError(
            "explode() must be planned through a Generate node (use it "
            "at the top level of a select list)")

    def name(self):
        return "col"  # the reference's default generator output name

    def __repr__(self):
        return f"explode({self.children[0]!r})"


def contains_explode(e: Expression) -> bool:
    if isinstance(e, Explode):
        return True
    return any(contains_explode(c) for c in e.children)


def extract_generators(plan, exprs):
    """Pull explode() out of a projection into a Generate plan node
    (the reference's ExtractGenerator analyzer rule): at most one
    generator per select list, only at top level / under an alias."""
    from .expr import Alias, ColumnRef
    from .plan import logical as L
    if not any(contains_explode(e) for e in exprs):
        return plan, list(exprs)
    gens = []
    out = []
    taken = set(plan.schema().names)
    for e in exprs:
        base, want = (e.child, e.name()) if isinstance(e, Alias) else \
            (e, None)
        if isinstance(base, Explode):
            name = want or "col"
            if name in taken:
                raise AnalysisError(
                    f"generator output name {name!r} collides")
            gens.append((base, name))
            out.append(ColumnRef(name))
            continue
        if contains_explode(e):
            raise AnalysisError(
                "explode() is only supported at the top level of a "
                "select list (optionally aliased)")
        out.append(e)
    if len(gens) != 1:
        raise AnalysisError(
            "only one explode() per select list is supported")
    gen, name = gens[0]
    plan = L.Generate(plan, gen.children[0], name, outer=gen.outer)
    return plan, out

"""User-facing expression constructors (the reference's `functions.scala`)."""

from __future__ import annotations

from typing import Optional, Union

from . import types as T
from .expr import (CaseWhen, ColumnRef, ConcatLit, DateAdd, EqNullSafe,
                   Expression, ExtractDay, ExtractMonth, ExtractYear,
                   Literal, Lower, StringLength, Trim, Upper, date_literal)
from .expr_agg import (AggExpr, Avg, Count, CountDistinct, Max, Min,
                       StddevPop, StddevSamp, Sum, VariancePop,
                       VarianceSamp)


def col(name: str) -> ColumnRef:
    return ColumnRef(name)


def lit(value, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal(value, dtype)


def to_date(s: str) -> Literal:
    """A DATE literal from 'YYYY-MM-DD'."""
    return date_literal(s)


def decimal_lit(value: Union[int, float, str], scale: int = 2) -> Literal:
    return Literal(float(value), T.DecimalType(38, scale))


def _expr(e) -> Expression:
    return e if isinstance(e, Expression) else col(e) if isinstance(e, str) \
        else Literal(e)


def sum(e) -> Sum:  # noqa: A001 - mirrors pyspark.sql.functions naming
    return Sum(_expr(e))


def avg(e) -> Avg:
    return Avg(_expr(e))


def count(e="*") -> Count:
    if e is None or (isinstance(e, str) and e == "*"):
        return Count(None)
    return Count(_expr(e))


def min(e) -> Min:  # noqa: A001
    return Min(_expr(e))


def max(e) -> Max:  # noqa: A001
    return Max(_expr(e))


def year(e) -> ExtractYear:
    return ExtractYear(_expr(e))


def month(e) -> ExtractMonth:
    return ExtractMonth(_expr(e))


def day(e) -> ExtractDay:
    return ExtractDay(_expr(e))


dayofmonth = day


def date_add(e, days) -> DateAdd:
    return DateAdd(_expr(e), _expr(days))


def date_sub(e, days) -> DateAdd:
    from .expr import Neg
    d = _expr(days)
    if isinstance(d, Literal) and isinstance(d.value, int):
        return DateAdd(_expr(e), Literal(-d.value))
    return DateAdd(_expr(e), Neg(d))


def stddev(e) -> StddevSamp:
    return StddevSamp(_expr(e))


stddev_samp = stddev


def stddev_pop(e) -> StddevPop:
    return StddevPop(_expr(e))


def variance(e) -> VarianceSamp:
    return VarianceSamp(_expr(e))


var_samp = variance


def var_pop(e) -> VariancePop:
    return VariancePop(_expr(e))


def count_distinct(e) -> CountDistinct:
    return CountDistinct(_expr(e))


countDistinct = count_distinct


def upper(e) -> Upper:
    return Upper(_expr(e))


def lower(e) -> Lower:
    return Lower(_expr(e))


def trim(e) -> Trim:
    return Trim(_expr(e))


def length(e) -> StringLength:
    return StringLength(_expr(e))


def concat(*parts) -> Expression:
    """concat of string literals around ONE string column (general
    column-column concat needs a product dictionary — unsupported)."""
    exprs = [_expr(p) for p in parts]
    if any(isinstance(p, Literal) and p.value is None for p in exprs):
        return Literal(None, T.STRING)  # NULL in -> NULL out
    col_idx = [i for i, p in enumerate(exprs)
               if not isinstance(p, Literal)]
    if len(col_idx) != 1:
        from .expr import AnalysisError
        raise AnalysisError("concat supports exactly one non-literal "
                            "string argument")
    i = col_idx[0]
    prefix = "".join(str(p.value) for p in exprs[:i])
    suffix = "".join(str(p.value) for p in exprs[i + 1:])
    return ConcatLit(exprs[i], prefix, suffix)


def eq_null_safe(a, b) -> EqNullSafe:
    """a <=> b (reference: EqualNullSafe)."""
    return EqNullSafe(_expr(a), _expr(b))


# window functions (spark_tpu.window has the Window/WindowSpec builders)
def row_number():
    from .window import row_number as f
    return f()


def rank():
    from .window import rank as f
    return f()


def dense_rank():
    from .window import dense_rank as f
    return f()


def lag(e, offset: int = 1, default=None):
    from .window import lag as f
    return f(e, offset, default)


def lead(e, offset: int = 1, default=None):
    from .window import lead as f
    return f(e, offset, default)


def pmod(dividend, divisor) -> Expression:
    """Positive modulo: result in [0, |divisor|) (reference: pmod())."""
    from .expr import Pmod
    return Pmod(_expr(dividend), _expr(divisor))


class _WhenBuilder(Expression):
    """when(cond, val).when(...).otherwise(...) chain (functions.scala when)."""

    def __init__(self, branches):
        self._branches = branches
        self.children = ()

    def when(self, cond: Expression, value) -> "_WhenBuilder":
        return _WhenBuilder(self._branches + [(cond, _expr(value))])

    def otherwise(self, value) -> CaseWhen:
        return CaseWhen(self._branches, _expr(value))

    def _case(self) -> CaseWhen:
        return CaseWhen(self._branches, None)

    def dtype(self, schema):
        return self._case().dtype(schema)

    def nullable(self, schema):
        return True

    def eval(self, batch):
        return self._case().eval(batch)

    def references(self):
        return self._case().references()


def when(cond: Expression, value) -> _WhenBuilder:
    return _WhenBuilder([(cond, _expr(value))])


# ---------------------------------------------------------------------------
# Round-4 breadth: math / datetime / string / null / extended aggregates
# (registry-driven SQL names live in sql/registry.py; these are the
# pyspark-shaped DSL constructors)
# ---------------------------------------------------------------------------

from . import expr_fns as _X  # noqa: E402
from .expr_agg import (AnyValue as _AnyValue, AvgDistinct as _AvgDistinct,  # noqa: E402
                       BoolAnd as _BoolAnd, BoolOr as _BoolOr,
                       Corr as _Corr, CountIf as _CountIf,
                       CovarPop as _CovarPop, CovarSamp as _CovarSamp,
                       First as _First, Kurtosis as _Kurtosis,
                       Last as _Last, Skewness as _Skewness,
                       SumDistinct as _SumDistinct)


def _u1(cls):
    def f(e):
        return cls(_expr(e))
    return f


abs = _u1(_X.Abs)  # noqa: A001
sqrt = _u1(_X.Sqrt)
cbrt = _u1(_X.Cbrt)
exp = _u1(_X.Exp)
expm1 = _u1(_X.Expm1)
log = _u1(_X.Ln)
log10 = _u1(_X.Log10)
log2 = _u1(_X.Log2)
log1p = _u1(_X.Log1p)
sin = _u1(_X.Sin)
cos = _u1(_X.Cos)
tan = _u1(_X.Tan)
asin = _u1(_X.Asin)
acos = _u1(_X.Acos)
atan = _u1(_X.Atan)
sinh = _u1(_X.Sinh)
cosh = _u1(_X.Cosh)
tanh = _u1(_X.Tanh)
degrees = _u1(_X.Degrees)
radians = _u1(_X.Radians)
rint = _u1(_X.Rint)
signum = _u1(_X.Signum)
ceil = _u1(_X.Ceil)
floor = _u1(_X.Floor)
factorial = _u1(_X.Factorial)
bit_count = _u1(_X.BitCount)
bitwise_not = _u1(_X.BitwiseNot)
isnan = _u1(_X.IsNan)
quarter = _u1(_X.Quarter)
dayofweek = _u1(_X.DayOfWeek)
weekday = _u1(_X.WeekDay)
dayofyear = _u1(_X.DayOfYear)
weekofyear = _u1(_X.WeekOfYear)
last_day = _u1(_X.LastDay)
ltrim = _u1(_X.Ltrim)
rtrim = _u1(_X.Rtrim)
reverse = _u1(_X.Reverse)
initcap = _u1(_X.InitCap)
ascii = _u1(_X.Ascii)  # noqa: A001


def round(e, scale: int = 0):  # noqa: A001
    return _X.Round(_expr(e), scale)


def pow(a, b):  # noqa: A001
    return _X.Pow(_expr(a), _expr(b))


power = pow


def atan2(a, b):
    return _X.Atan2(_expr(a), _expr(b))


def hypot(a, b):
    return _X.Hypot(_expr(a), _expr(b))


def shiftleft(e, n):
    return _X.ShiftLeft(_expr(e), _expr(n))


def shiftright(e, n):
    return _X.ShiftRight(_expr(e), _expr(n))


def greatest(*args):
    return _X.Greatest(*[_expr(a) for a in args])


def least(*args):
    return _X.Least(*[_expr(a) for a in args])


def coalesce(*args):
    from .expr import Coalesce
    return Coalesce(*[_expr(a) for a in args])


def nvl(a, b):
    return _X.Nvl(_expr(a), _expr(b))


ifnull = nvl


def nvl2(a, b, c):
    return _X.Nvl2(_expr(a), _expr(b), _expr(c))


def nullif(a, b):
    return _X.NullIf(_expr(a), _expr(b))


def nanvl(a, b):
    return _X.Nanvl(_expr(a), _expr(b))


def expr_if(cond, a, b):
    return _X.If(cond, _expr(a), _expr(b))


def next_day(e, day_name: str):
    return _X.NextDay(_expr(e), day_name)


def add_months(e, n):
    return _X.AddMonths(_expr(e), _expr(n))


def months_between(end, start):
    return _X.MonthsBetween(_expr(end), _expr(start))


def datediff(end, start):
    return _X.DateDiff(_expr(end), _expr(start))


def trunc(e, fmt: str):
    return _X.TruncDate(_expr(e), fmt)


def make_date(y, m, d):
    return _X.MakeDate(_expr(y), _expr(m), _expr(d))


def lpad(e, length: int, pad: str = " "):
    return _X.Lpad(_expr(e), length, pad)


def rpad(e, length: int, pad: str = " "):
    return _X.Rpad(_expr(e), length, pad)


def translate(e, matching: str, replace: str):
    return _X.Translate(_expr(e), matching, replace)


def repeat(e, n: int):
    return _X.Repeat(_expr(e), n)


def regexp_replace(e, pattern: str, replacement: str):
    return _X.RegexpReplace(_expr(e), pattern, replacement)


def regexp_extract(e, pattern: str, idx: int = 1):
    return _X.RegexpExtract(_expr(e), pattern, idx)


def rlike(e, pattern: str):
    return _X.RLike(_expr(e), pattern)


def instr(e, sub: str):
    return _X.Instr(_expr(e), sub)


def contains(e, sub: str):
    return _X.Contains(_expr(e), sub)


def startswith(e, prefix: str):
    return _X.StartsWith(_expr(e), prefix)


def endswith(e, suffix: str):
    return _X.EndsWith(_expr(e), suffix)


def replace(e, search: str, replacement: str = ""):
    return _X.StringReplace(_expr(e), search, replacement)


# extended aggregates
def first(e, ignorenulls: bool = False):
    return _First(_expr(e), ignorenulls)


def last(e, ignorenulls: bool = False):
    return _Last(_expr(e), ignorenulls)


def any_value(e):
    return _AnyValue(_expr(e))


def corr(x, y):
    return _Corr(_expr(x), _expr(y))


def covar_samp(x, y):
    return _CovarSamp(_expr(x), _expr(y))


def covar_pop(x, y):
    return _CovarPop(_expr(x), _expr(y))


def skewness(e):
    return _Skewness(_expr(e))


def kurtosis(e):
    return _Kurtosis(_expr(e))


def bool_and(e):
    return _BoolAnd(_expr(e))


def bool_or(e):
    return _BoolOr(_expr(e))


def count_if(e):
    return _CountIf(_expr(e))


def sum_distinct(e):
    return _SumDistinct(_expr(e))


def avg_distinct(e):
    return _AvgDistinct(_expr(e))


# -- Python UDFs (ArrowEvalPythonExec.scala:1 / worker.py:504 analog) -------

from .udf import pandas_udf, udf  # noqa: E402,F401


# -- arrays (collectionOperations.scala / complexTypeCreator.scala) ---------

from . import expr_array as _A  # noqa: E402


def array(*cols):
    return _A.MakeArray(*[_expr(c) for c in cols])


def size(c):
    return _A.Size(_expr(c))


def array_contains(c, value):
    return _A.ArrayContains(_expr(c), value)


def element_at(c, index):
    return _A.ElementAt(_expr(c), _expr(index))


def explode(c):
    return _A.Explode(_expr(c))


def explode_outer(c):
    return _A.Explode(_expr(c), outer=True)


# -- positional aggregates (Percentile.scala / collect.scala) ---------------

from .expr_agg import (CollectList as _CollectList,  # noqa: E402
                       CollectSet as _CollectSet, Median as _Median,
                       Percentile as _Percentile)


def percentile(e, q):
    return _Percentile(_expr(e), q)


def percentile_approx(e, q, accuracy=None):
    """Exact percentile (better than the required accuracy bound of the
    reference's ApproximatePercentile.scala:1 — the device sort makes
    exact as cheap as approximate)."""
    return _Percentile(_expr(e), q)


approx_percentile = percentile_approx


def median(e):
    return _Median(_expr(e))


def collect_list(e):
    return _CollectList(_expr(e))


def collect_set(e):
    return _CollectSet(_expr(e))


def window(ts, duration):
    """Tumbling event-time window start (reference: TimeWindow); used as
    a streaming group key with with_watermark for event-time
    aggregation."""
    return _X.TumbleWindow(_expr(ts), duration)

"""User-facing expression constructors (the reference's `functions.scala`)."""

from __future__ import annotations

from typing import Optional, Union

from . import types as T
from .expr import (CaseWhen, ColumnRef, Expression, ExtractYear, Literal,
                   date_literal)
from .expr_agg import AggExpr, Avg, Count, Max, Min, Sum


def col(name: str) -> ColumnRef:
    return ColumnRef(name)


def lit(value, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal(value, dtype)


def to_date(s: str) -> Literal:
    """A DATE literal from 'YYYY-MM-DD'."""
    return date_literal(s)


def decimal_lit(value: Union[int, float, str], scale: int = 2) -> Literal:
    return Literal(float(value), T.DecimalType(38, scale))


def _expr(e) -> Expression:
    return e if isinstance(e, Expression) else col(e) if isinstance(e, str) \
        else Literal(e)


def sum(e) -> Sum:  # noqa: A001 - mirrors pyspark.sql.functions naming
    return Sum(_expr(e))


def avg(e) -> Avg:
    return Avg(_expr(e))


def count(e="*") -> Count:
    if e is None or (isinstance(e, str) and e == "*"):
        return Count(None)
    return Count(_expr(e))


def min(e) -> Min:  # noqa: A001
    return Min(_expr(e))


def max(e) -> Max:  # noqa: A001
    return Max(_expr(e))


def year(e) -> ExtractYear:
    return ExtractYear(_expr(e))


def pmod(dividend, divisor) -> Expression:
    """Positive modulo: result in [0, |divisor|) (reference: pmod())."""
    from .expr import Pmod
    return Pmod(_expr(dividend), _expr(divisor))


class _WhenBuilder(Expression):
    """when(cond, val).when(...).otherwise(...) chain (functions.scala when)."""

    def __init__(self, branches):
        self._branches = branches
        self.children = ()

    def when(self, cond: Expression, value) -> "_WhenBuilder":
        return _WhenBuilder(self._branches + [(cond, _expr(value))])

    def otherwise(self, value) -> CaseWhen:
        return CaseWhen(self._branches, _expr(value))

    def _case(self) -> CaseWhen:
        return CaseWhen(self._branches, None)

    def dtype(self, schema):
        return self._case().dtype(schema)

    def nullable(self, schema):
        return True

    def eval(self, batch):
        return self._case().eval(batch)

    def references(self):
        return self._case().references()


def when(cond: Expression, value) -> _WhenBuilder:
    return _WhenBuilder([(cond, _expr(value))])

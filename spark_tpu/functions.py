"""User-facing expression constructors (the reference's `functions.scala`)."""

from __future__ import annotations

from typing import Optional, Union

from . import types as T
from .expr import (CaseWhen, ColumnRef, ConcatLit, DateAdd, EqNullSafe,
                   Expression, ExtractDay, ExtractMonth, ExtractYear,
                   Literal, Lower, StringLength, Trim, Upper, date_literal)
from .expr_agg import (AggExpr, Avg, Count, CountDistinct, Max, Min,
                       StddevPop, StddevSamp, Sum, VariancePop,
                       VarianceSamp)


def col(name: str) -> ColumnRef:
    return ColumnRef(name)


def lit(value, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal(value, dtype)


def to_date(s: str) -> Literal:
    """A DATE literal from 'YYYY-MM-DD'."""
    return date_literal(s)


def decimal_lit(value: Union[int, float, str], scale: int = 2) -> Literal:
    return Literal(float(value), T.DecimalType(38, scale))


def _expr(e) -> Expression:
    return e if isinstance(e, Expression) else col(e) if isinstance(e, str) \
        else Literal(e)


def sum(e) -> Sum:  # noqa: A001 - mirrors pyspark.sql.functions naming
    return Sum(_expr(e))


def avg(e) -> Avg:
    return Avg(_expr(e))


def count(e="*") -> Count:
    if e is None or (isinstance(e, str) and e == "*"):
        return Count(None)
    return Count(_expr(e))


def min(e) -> Min:  # noqa: A001
    return Min(_expr(e))


def max(e) -> Max:  # noqa: A001
    return Max(_expr(e))


def year(e) -> ExtractYear:
    return ExtractYear(_expr(e))


def month(e) -> ExtractMonth:
    return ExtractMonth(_expr(e))


def day(e) -> ExtractDay:
    return ExtractDay(_expr(e))


dayofmonth = day


def date_add(e, days) -> DateAdd:
    return DateAdd(_expr(e), _expr(days))


def date_sub(e, days) -> DateAdd:
    from .expr import Neg
    d = _expr(days)
    if isinstance(d, Literal) and isinstance(d.value, int):
        return DateAdd(_expr(e), Literal(-d.value))
    return DateAdd(_expr(e), Neg(d))


def stddev(e) -> StddevSamp:
    return StddevSamp(_expr(e))


stddev_samp = stddev


def stddev_pop(e) -> StddevPop:
    return StddevPop(_expr(e))


def variance(e) -> VarianceSamp:
    return VarianceSamp(_expr(e))


var_samp = variance


def var_pop(e) -> VariancePop:
    return VariancePop(_expr(e))


def count_distinct(e) -> CountDistinct:
    return CountDistinct(_expr(e))


countDistinct = count_distinct


def upper(e) -> Upper:
    return Upper(_expr(e))


def lower(e) -> Lower:
    return Lower(_expr(e))


def trim(e) -> Trim:
    return Trim(_expr(e))


def length(e) -> StringLength:
    return StringLength(_expr(e))


def concat(*parts) -> Expression:
    """concat of string literals around ONE string column (general
    column-column concat needs a product dictionary — unsupported)."""
    exprs = [_expr(p) for p in parts]
    col_idx = [i for i, p in enumerate(exprs)
               if not isinstance(p, Literal)]
    if len(col_idx) != 1:
        from .expr import AnalysisError
        raise AnalysisError("concat supports exactly one non-literal "
                            "string argument")
    i = col_idx[0]
    prefix = "".join(str(p.value) for p in exprs[:i])
    suffix = "".join(str(p.value) for p in exprs[i + 1:])
    return ConcatLit(exprs[i], prefix, suffix)


def eq_null_safe(a, b) -> EqNullSafe:
    """a <=> b (reference: EqualNullSafe)."""
    return EqNullSafe(_expr(a), _expr(b))


# window functions (spark_tpu.window has the Window/WindowSpec builders)
def row_number():
    from .window import row_number as f
    return f()


def rank():
    from .window import rank as f
    return f()


def dense_rank():
    from .window import dense_rank as f
    return f()


def lag(e, offset: int = 1, default=None):
    from .window import lag as f
    return f(e, offset, default)


def lead(e, offset: int = 1, default=None):
    from .window import lead as f
    return f(e, offset, default)


def pmod(dividend, divisor) -> Expression:
    """Positive modulo: result in [0, |divisor|) (reference: pmod())."""
    from .expr import Pmod
    return Pmod(_expr(dividend), _expr(divisor))


class _WhenBuilder(Expression):
    """when(cond, val).when(...).otherwise(...) chain (functions.scala when)."""

    def __init__(self, branches):
        self._branches = branches
        self.children = ()

    def when(self, cond: Expression, value) -> "_WhenBuilder":
        return _WhenBuilder(self._branches + [(cond, _expr(value))])

    def otherwise(self, value) -> CaseWhen:
        return CaseWhen(self._branches, _expr(value))

    def _case(self) -> CaseWhen:
        return CaseWhen(self._branches, None)

    def dtype(self, schema):
        return self._case().dtype(schema)

    def nullable(self, schema):
        return True

    def eval(self, batch):
        return self._case().eval(batch)

    def references(self):
        return self._case().references()


def when(cond: Expression, value) -> _WhenBuilder:
    return _WhenBuilder([(cond, _expr(value))])

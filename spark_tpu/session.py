"""SparkSession analog: catalog + conf + entry points.

Reference: `sql/core/src/main/scala/org/apache/spark/sql/SparkSession.scala:83`
(builder, per-session conf/catalog/state) and `DataFrameReader`.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Dict, Optional

import pandas as pd
import pyarrow as pa

from .columnar import Batch
from .config import Conf
from .dataframe import DataFrame
from .io.sources import ArrowTableSource, ParquetSource, TableSource
from .plan import logical as L

#: context-local active session (the SQL service pins one per worker
#: thread with `session.as_active()`); falls back to the process-global
#: singleton below, preserving the historical single-caller behavior
_ACTIVE: ContextVar[Optional["SparkTpuSession"]] = ContextVar(
    "spark_tpu_active_session", default=None)


class _ActiveSessionMeta(type):
    """`SparkTpuSession._active` used to be a process-global class
    attribute; under the concurrent SQL service it resolves per context
    (each worker thread sees the session it activated) with the global
    as fallback. Reads and writes of the class attribute keep working
    unchanged — tests assign `SparkTpuSession._active = None` and the
    builder reads it — via this metaclass property."""

    @property
    def _active(cls) -> Optional["SparkTpuSession"]:
        s = _ACTIVE.get()
        return s if s is not None else cls._global_active

    @_active.setter
    def _active(cls, value: Optional["SparkTpuSession"]) -> None:
        cls._global_active = value
        _ACTIVE.set(value)


class SparkTpuSession(metaclass=_ActiveSessionMeta):
    _global_active: Optional["SparkTpuSession"] = None

    def __init__(self, conf: Optional[Conf] = None,
                 register_active: bool = True):
        self.conf = conf or Conf()
        from .catalog import Catalog
        self.catalog: Catalog = Catalog(self)
        self._stage_cache: Dict[str, object] = {}
        # observability spine (observability/): the listener bus every
        # event-log line / trace file / metrics flush hangs off, the
        # process metrics registry, XLA stage-cost memo, and the
        # session-unique event-log identity + query-id sequence
        from .observability import ListenerBus, MetricsRegistry
        from .observability.sinks import (install_default_listeners,
                                          make_app_id)
        self.listeners = ListenerBus()
        self.metrics = MetricsRegistry()
        self.app_id = make_app_id()
        self._stage_costs: Dict[str, dict] = {}
        # memoized jaxpr-analysis findings per stage key (analysis/)
        self._analysis_memo: Dict[str, list] = {}
        self._query_seq = 0
        install_default_listeners(self)
        # plan-fingerprint data cache (reference: CacheManager.scala):
        # requested marks fill with materialized Arrow tables on first
        # action; later plans substitute equal subtrees with cached scans
        self._cache_requests: Dict[str, object] = {}  # fp -> LogicalPlan
        from .service.arbiter import RESULT_CACHE_BYTES_KEY, ResultCache
        # standalone sessions keep the pre-service unbounded cache
        # unless the bound is explicitly configured: a cache()-marked
        # table larger than a default bound would silently recompute
        # per reference. Pooled sessions get this replaced by the
        # arbiter's shared, conf-bounded cache (service/pool.py).
        self._data_cache = ResultCache(
            max_bytes=(int(self.conf.get(RESULT_CACHE_BYTES_KEY))
                       if self.conf.is_explicitly_set(RESULT_CACHE_BYTES_KEY)
                       else 0),
            metrics=self.metrics)
        self._implicit_cache_fps: set = set()
        self._exec_depth = 0  # outermost-execution tracking for eviction
        # plan-fingerprint -> {kind:tag -> capacity} discovered by the
        # AQE overflow loop; repeated executions seed these and skip the
        # overflow->re-jit ramp
        self._aqe_caps: Dict[str, Dict[str, int]] = {}
        from .udf import UDFRegistration
        self.udf = UDFRegistration(self)
        # out-of-process UDF worker pool (udf_worker/pool.py): created
        # eagerly (a pool object spawns nothing until first checkout)
        # so lockwatch can wrap its cv at session install time; bounds
        # are refreshed from conf at each worker-mode evaluation.
        # Workers are reused across this session's queries; idle ones
        # reap after udf.pool.idleTimeoutMs, and a worker's stdin EOF
        # on process exit ends the child, so none outlives the engine.
        from .udf_worker.pool import UdfWorkerPool
        self._udf_pool = UdfWorkerPool(
            int(self.conf.get("spark_tpu.sql.udf.pool.maxWorkers")),
            float(self.conf.get("spark_tpu.sql.udf.pool.idleTimeoutMs")),
            metrics=self.metrics)
        if register_active:
            SparkTpuSession._active = self

    @contextlib.contextmanager
    def as_active(self):
        """Pin this session as the context-local active session (what
        `builder().get_or_create()` returns) for the enclosed block —
        the SQL service wraps each query execution in this so pooled
        sessions never stomp the process-global singleton or each
        other."""
        token = _ACTIVE.set(self)
        try:
            yield self
        finally:
            _ACTIVE.reset(token)

    # -- observability ------------------------------------------------------

    def _next_query_id(self) -> int:
        self._query_seq += 1
        return self._query_seq

    def add_listener(self, listener) -> None:
        """Register a QueryListener on the session bus (the
        SparkContext.addSparkListener seat)."""
        self.listeners.register(listener)

    def remove_listener(self, listener) -> None:
        self.listeners.unregister(listener)

    addListener = add_listener
    removeListener = remove_listener

    def warmup(self) -> int:
        """Warm-start the in-memory stage cache from the persistent
        compile cache (execution/compile_cache.py): replay the
        manifest of recently-seen stage keys, deserializing each
        entry whose environment fingerprint matches this process —
        deserialization only, no compiles. Returns entries installed
        (0 when spark_tpu.sql.compileCache.enabled is off). The
        SQL service calls the pooled equivalent at startup
        (compileCache.warmStart)."""
        from .execution.compile_cache import warm_start
        return warm_start(self._stage_cache, self.conf, self.metrics)

    def cancel(self, query_id: int) -> bool:
        """Request cooperative cancellation of a query currently
        executing on this session (the SparkContext.cancelJobGroup
        seat, execution/lifecycle.py): the running execution raises a
        structured QueryCancelledError at its next boundary — chunk,
        stage attempt, retry backoff, queue/lease wait — releasing
        every lease/worker/checkpoint it holds. Returns False when no
        execution with that query_id is registered (already finished,
        or never started). Callable from any thread."""
        from .execution import lifecycle
        return lifecycle.cancel(self.app_id, query_id)

    def decommission_shards(self, shards) -> None:
        """Gracefully drain the given mesh positions (elastic mesh,
        parallel/elastic.py): a running mesh stream checkpoints at its
        next chunk boundary and continues on the reduced gang; the
        drained devices stay excluded for later queries. The
        BlockManagerDecommissioner seat."""
        from .parallel.elastic import decommission_shards
        decommission_shards(self, shards)

    # -- data cache ---------------------------------------------------------

    @staticmethod
    def _plan_fingerprint(plan) -> str:
        """tree_string + each scan source's identity stamp: a Parquet
        rewrite or table re-registration changes the fingerprint, so a
        cached materialization can never match stale data (round-3
        ADVICE medium)."""
        tokens = [s.source.cache_token() for s in L.iter_scans(plan)]
        return plan.tree_string() + f"#src{tokens!r}"

    def mark_cache(self, plan, implicit: bool = False) -> None:
        fp = self._plan_fingerprint(plan)
        self._cache_requests[fp] = plan
        if implicit:
            # statement-scoped (e.g. WITH-clause views): evicted when the
            # outermost execution finishes, so implicit materializations
            # neither go stale nor grow session memory unboundedly
            self._implicit_cache_fps.add(fp)

    def uncache(self, plan) -> None:
        fp = self._plan_fingerprint(plan)
        self._cache_requests.pop(fp, None)
        self._data_cache.pop(fp, None)
        self._implicit_cache_fps.discard(fp)

    def _evict_implicit_caches(self) -> None:
        """Statement-scoped DATA lifetime: drop materialized tables but
        KEEP the requests/marks, so re-executing the same statement
        still dedupes a multiply-referenced CTE within that execution."""
        for fp in self._implicit_cache_fps:
            self._data_cache.pop(fp, None)

    # -- builder ------------------------------------------------------------

    class Builder:
        def __init__(self):
            self._conf = Conf()

        def config(self, key: str, value) -> "SparkTpuSession.Builder":
            self._conf.set(key, value)
            return self

        def get_or_create(self) -> "SparkTpuSession":
            if SparkTpuSession._active is not None:
                return SparkTpuSession._active
            return SparkTpuSession(self._conf)

        getOrCreate = get_or_create

    @classmethod
    def builder(cls) -> "SparkTpuSession.Builder":
        return cls.Builder()

    # -- entry points -------------------------------------------------------

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Range(int(start), int(end), int(step)))

    def create_dataframe(self, data, name: str = "df") -> DataFrame:
        if isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, pa.Table):
            table = data
        elif isinstance(data, dict):
            table = pa.table(data)
        else:
            raise TypeError(f"cannot create DataFrame from {type(data)}")
        source = ArrowTableSource(name, table)
        return DataFrame(self, L.Scan(source))

    createDataFrame = create_dataframe

    def register_table(self, name: str, source_or_table) -> None:
        # invalidate cached materializations referencing this name (a
        # re-registered table must never serve stale cached results)
        stale = [fp for fp, plan in self._cache_requests.items()
                 if any(s.source.name == name for s in L.iter_scans(plan))]
        for fp in stale:
            self._cache_requests.pop(fp, None)
            self._data_cache.pop(fp, None)
            self._implicit_cache_fps.discard(fp)
        # free the replaced source's device-resident batches (they are
        # unreachable under the new token and would pin HBM until LRU
        # pressure evicted them)
        old = self.catalog.get(name)
        if old is not None:
            token = old.cache_token()
            if token is not None:
                from .io.device_cache import CACHE
                CACHE.invalidate_token(token)
        if isinstance(source_or_table, TableSource):
            self.catalog[name] = source_or_table
        elif isinstance(source_or_table, pa.Table):
            self.catalog[name] = ArrowTableSource(name, source_or_table)
        elif isinstance(source_or_table, pd.DataFrame):
            self.catalog[name] = ArrowTableSource(
                name, pa.Table.from_pandas(source_or_table,
                                           preserve_index=False))
        elif isinstance(source_or_table, DataFrame):
            self.catalog[name] = ArrowTableSource(
                name, source_or_table.collect())
        else:
            raise TypeError(f"cannot register {type(source_or_table)}")

    def table(self, name: str) -> DataFrame:
        if name not in self.catalog:
            raise KeyError(f"table {name!r} not found; "
                           f"known: {sorted(self.catalog)}")
        return DataFrame(self, L.Scan(self.catalog[name]))

    def read_parquet(self, path: str, name: Optional[str] = None) -> DataFrame:
        return DataFrame(self, L.Scan(ParquetSource(path, name)))

    def read_csv(self, path: str, name: Optional[str] = None,
                 **options) -> DataFrame:
        from .io.sources import CsvSource
        return DataFrame(self, L.Scan(CsvSource(path, name, **options)))

    def read_json(self, path: str, name: Optional[str] = None) -> DataFrame:
        from .io.sources import JsonSource
        return DataFrame(self, L.Scan(JsonSource(path, name)))

    def file_stream(self, path: str, schema_df=None,
                    format: str = "parquet"):
        """Directory-tailing streaming source (the readStream analog):
        returns a FileStreamSource whose `.to_df()` feeds
        `DataFrame.write_stream`. Offsets are a persisted seen-file
        log under the query's checkpoint; corrupt files quarantine
        instead of wedging the stream (see
        spark_tpu.streaming.source.file.strict)."""
        from .streaming import FileStreamSource
        return FileStreamSource(self, path, schema_df=schema_df,
                                format=format)

    def network_stream(self, host: str, port: int, schema_df):
        """Socket streaming source (io/network_source.py): length-
        framed Arrow-IPC record batches over TCP, each frame persisted
        under the query's checkpoint BEFORE it becomes a visible
        offset, with a reconnect/backoff ladder (see the
        spark_tpu.streaming.source.network.* keys). Returns a
        NetworkStreamSource whose `.to_df()` feeds
        `DataFrame.write_stream`."""
        from .io.network_source import NetworkStreamSource
        return NetworkStreamSource(self, host, port, schema_df)

    def long_accumulator(self, name: str = "acc") -> "Accumulator":
        return Accumulator(name, 0)

    def double_accumulator(self, name: str = "acc") -> "Accumulator":
        return Accumulator(name, 0.0)

    longAccumulator = long_accumulator
    doubleAccumulator = double_accumulator

    def sql(self, query: str) -> DataFrame:
        from .sql.parser import parse_sql
        plan = parse_sql(query, self)
        return DataFrame(self, plan)


class Accumulator:
    """Driver-side mergeable counter (reference: AccumulatorV2.scala:44).
    Python UDFs and grouped-map functions run host-side, so updates are
    plain in-process adds — the task->driver merge protocol collapses
    away; per-operator engine metrics ride the psum'd stats channel
    instead (metric/SQLMetrics.scala:40 analog in ExecContext)."""

    def __init__(self, name: str, value=0):
        self.name = name
        self._value = value

    def add(self, v) -> None:
        self._value += v

    @property
    def value(self):
        return self._value

    def reset(self) -> None:
        self._value = type(self._value)()

    def __repr__(self):
        return f"Accumulator({self.name}={self._value!r})"

"""Partial-progress recovery: chunk-granular retry, stage-output
reuse, and checkpoint/restore for streaming + mesh execution.

The PR-2 recovery layer is whole-query granular: `_execute_recover`
loops the entire `_execute_batch_inner`, so a fault in chunk 37 of a
streaming aggregate re-ingests from chunk 0, and a lost mesh host
throws away all accumulated state. The reference's resilience story is
*granular* — lineage + task-level retry re-runs one partition, and
completed shuffle files survive downstream failures (the RDD lineage
model of Zaharia et al., NSDI'12). This module restores that
granularity at the three seams this engine has:

- **ChunkRetrier** — per-chunk retry inside the streaming drivers
  (`streaming_agg.py` scan/spill/mesh variants and `external.py`).
  The carry state (accumulator tables, chunk cursor) is only advanced
  after a chunk succeeds, so a TRANSIENT/UNAVAILABLE fault replays
  exactly the failed chunk —
  `spark_tpu.execution.chunkRetry.{enabled,maxRetries}`. The
  `stream_chunk` fault seam fires once per chunk attempt here. The
  `load_chunks` ingest edge is NOT retried: a reader failure poisons
  the ChunkIterator (io/sources.py) and surfaces to the whole-query
  ladder, which restarts the stream against a fresh iterator.
- **StageOutputMemo** (inside RecoveryContext) — a per-query memo of
  completed stage outputs (streamed-aggregate splices, join build
  sides, generate materializations), the analog of shuffle files
  surviving a downstream task failure. When `_handle_failure`
  re-executes the query, completed upstream stages replay from the
  memo instead of re-running. Invalidated by epoch bump whenever a
  re-plan changes shapes (_ReplanRequest, mesh fallback, the OOM
  ladder's deviceBudget re-plan).
- **MeshCheckpoint** — every `checkpoint.everyChunks` chunks the mesh
  streaming driver snapshots its accumulator state device->host (as a
  partial-aggregate Arrow table, the exact shape a FINAL aggregate
  consumes); on mesh failure the single-device fallback resumes at the
  checkpointed chunk cursor instead of chunk 0. The `mesh_checkpoint`
  fault seam fires at each snapshot point.

All recovery actions flow through the executor's `_record_fault`
(`chunk_retry`, `stage_reuse`, `checkpoint_restore`) into
fault_summary, the event log and history; the process metrics registry
counts `rec_chunks_replayed`, `rec_stages_reused`, `rec_ckpt_bytes`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from .failures import FailureClass, RetryPolicy, classify

CHUNK_RETRY_ENABLED_KEY = "spark_tpu.execution.chunkRetry.enabled"
CHUNK_RETRY_MAX_KEY = "spark_tpu.execution.chunkRetry.maxRetries"
CHECKPOINT_EVERY_KEY = "spark_tpu.execution.checkpoint.everyChunks"
BACKOFF_KEY = "spark_tpu.execution.backoffMs"

#: failure classes a single chunk replay can recover (OOM descends the
#: executor ladder instead — replaying the same chunk into the same
#: exhausted HBM would spin the per-chunk budget for nothing)
_RETRYABLE = (FailureClass.TRANSIENT, FailureClass.TIMEOUT)


@dataclass
class MeshCheckpoint:
    """Device->host snapshot of a mesh stream's accumulator state:
    the partial-aggregate rows covering the first `cursor` chunks."""

    key: str
    cursor: int  # chunks folded into `table` (resume skips these)
    table: Any  # pyarrow.Table of partial-aggregate rows


class ChunkRetrier:
    """Per-chunk retry policy for the streaming drivers' COMPUTE steps.

    `run(step)` fires the `stream_chunk` chaos seam, executes the
    step, and — when chunk retry is enabled — replays the step on
    TRANSIENT/TIMEOUT failures under a fresh per-chunk RetryPolicy
    (the spark.task.maxFailures discipline: the budget is per task
    attempt, not per stream). The caller's carry state must only
    advance on success, so the pre-chunk state is the implicit
    snapshot the replay runs against.

    INGEST (`next(chunks)`) is deliberately NOT retried: a reader
    failure poisons the ChunkIterator (io/sources.py), so a replay
    could never succeed — and a post-cursor failure replayed on a
    single-pass iterator would silently skip rows. Ingest failures
    surface to the whole-query ladder, which restarts the stream
    against a fresh iterator.

    Donation caveat: the hot-path update steps donate their carried
    tables; a REAL mid-dispatch failure may have consumed them, in
    which case the replay itself fails — the original transient error
    is re-raised so the outer whole-query ladder still classifies the
    failure as retryable (degraded to PR-2 whole-stream granularity,
    never worse).
    """

    def __init__(self, conf, recovery: Optional["RecoveryContext"] = None,
                 site: str = "stream_chunk"):
        self.enabled = bool(conf.get(CHUNK_RETRY_ENABLED_KEY))
        self.max_retries = int(conf.get(CHUNK_RETRY_MAX_KEY))
        self.backoff_ms = float(conf.get(BACKOFF_KEY))
        self.recovery = recovery
        # chaos seam fired per attempt: "stream_chunk" for the compute
        # steps, "ingest_prefetch" for the prefetcher's host-decode step
        # (io/sources.py) — same retry policy, same recovery recording
        self.site = site

    def run(self, step, chunk: int = 0):
        from ..testing import faults
        from .lifecycle import checkpoint
        # cooperative cancellation boundary: every chunk of every
        # driver (streaming direct/spill/mesh + external collect)
        # passes through here, so a cancel/deadline lands within one
        # chunk of delivery (execution/lifecycle.py)
        checkpoint("chunk")
        policy: Optional[RetryPolicy] = None
        orig: Optional[Exception] = None
        while True:
            try:
                # chaos seam: one hit per chunk attempt (replays
                # re-fire, so multi-fault rules can target retries).
                # Literal site strings: the fault-site lint statically
                # proves each KNOWN_SITE has a wired fire() seam.
                if self.site == "ingest_prefetch":
                    faults.fire("ingest_prefetch")
                elif self.site == "udf_batch":
                    # seam fires INSIDE the step (python_eval's worker
                    # lane): the step must kill the in-flight worker
                    # before the injected error surfaces, so the
                    # fatal rule models a real SIGKILL mid-batch
                    pass
                else:
                    faults.fire("stream_chunk")
                return step()
            except Exception as e:  # noqa: BLE001 — classified below
                if not self.enabled or self.max_retries <= 0:
                    raise
                cls = classify(e)
                if cls is FailureClass.CANCELLED:
                    # lifecycle control, not a fault: never replayed,
                    # and never laundered into a saved `orig` transient
                    raise
                if cls not in _RETRYABLE:
                    if orig is not None:
                        # the replay hit a secondary non-retryable error
                        # (e.g. a donated buffer consumed by the failed
                        # dispatch): surface the ORIGINAL transient so
                        # the outer ladder still retries the stream
                        raise orig from e
                    raise
                if policy is None:
                    policy = RetryPolicy(self.max_retries, self.backoff_ms)
                slept = policy.attempt_retry()
                if slept is None:
                    raise  # per-chunk budget exhausted: outer ladder
                orig = e
                if self.recovery is not None:
                    self.recovery.chunk_replayed(e, chunk=chunk,
                                                 backoff_ms=slept)


class RecoveryContext:
    """Per-query-execution recovery state, created by the executor at
    every `execute_batch` / external-collect entry and threaded through
    the streaming drivers: the fault recorder, the stage-output memo,
    and the mesh checkpoint store."""

    def __init__(self, metrics=None, record=None):
        self.metrics = metrics  # session MetricsRegistry (or None)
        self._record = record   # QueryExecution._record_fault (or None)
        # stage-output memo: key -> (epoch, attempt, value). Keys are
        # (kind, id(node)) — node identities are stable across
        # recovery re-executions (the physical plan is only rebuilt on
        # re-plan, which bumps the epoch and orphans the old ids).
        self._memo: Dict[Tuple, Tuple[int, int, Any]] = {}
        self.epoch = 0
        self.checkpoints: Dict[str, MeshCheckpoint] = {}
        # per-stream progress watermark (checkpoint key -> chunks
        # consumed): lets a checkpoint restore report exactly how many
        # chunks the replay re-covers (progress - cursor), bounding the
        # elastic-mesh replay proof. Survives invalidate() like the
        # checkpoints it measures against.
        self._progress: Dict[str, int] = {}
        # set by _handle_failure once any recovery action was applied:
        # memo hits before the first failure are intra-attempt dedup,
        # not recovery, and must not pollute fault_summary
        self.in_recovery = False
        # recovery-attempt ordinal + per-(attempt, key) reuse dedup: a
        # re-execution may consult the same memo entry several times
        # (direct probe, then spill fallback), but that is ONE stage
        # replayed from the memo, not several
        self.attempt = 0
        self._reuse_logged: set = set()

    # -- recording ----------------------------------------------------------

    def record(self, action: str, exc=None, **extra) -> None:
        if self._record is not None:
            self._record(action, exc, **extra)

    def chunk_replayed(self, exc, chunk: int, backoff_ms: float) -> None:
        self.record("chunk_retry", exc, chunk=int(chunk),
                    backoff_ms=round(float(backoff_ms), 1))
        if self.metrics is not None:
            self.metrics.counter("rec_chunks_replayed").inc()

    # -- stage-output memo --------------------------------------------------

    def begin_recovery_attempt(self) -> None:
        """Called by the executor whenever a recovery action was
        applied and the query will re-execute: memo hits from here on
        are genuine stage reuse (and count once per attempt per key)."""
        self.in_recovery = True
        self.attempt += 1

    def memo_get(self, key: Tuple, label: str = ""):
        hit = self._memo.get(key)
        if hit is None or hit[0] != self.epoch:
            return None
        epoch, put_attempt, value = hit
        # "stage reuse" = an output from a PREVIOUS attempt survived
        # this re-execution; hits on entries put within the current
        # attempt are intra-attempt dedup (direct probe then spill
        # fallback touching the same build side), not recovery
        if self.in_recovery and put_attempt < self.attempt \
                and (self.attempt, key) not in self._reuse_logged:
            self._reuse_logged.add((self.attempt, key))
            self.record("stage_reuse", None, stage=str(label)[:120])
            if self.metrics is not None:
                self.metrics.counter("rec_stages_reused").inc()
        return value

    def memo_put(self, key: Tuple, value) -> None:
        self._memo[key] = (self.epoch, self.attempt, value)

    def invalidate(self) -> None:
        """A re-plan changed shapes (join strategy, mesh fallback, OOM
        deviceBudget reroute): memoized outputs no longer splice into
        the new plan. Checkpoints survive — they are host Arrow data
        validated by a plan-independent key."""
        self.epoch += 1
        self._memo.clear()

    # -- mesh checkpoints ---------------------------------------------------

    def save_checkpoint(self, key: str, cursor: int, snapshot) -> None:
        """Snapshot the mesh stream's accumulator state at `cursor`
        consumed chunks. `snapshot` is a thunk producing the host Arrow
        partial table (called AFTER the chaos seam, so an injected
        `mesh_checkpoint` fault models a failure at the snapshot point
        and leaves the PREVIOUS checkpoint intact)."""
        from ..testing import faults
        faults.fire("mesh_checkpoint")
        table = snapshot()
        self.checkpoints[key] = MeshCheckpoint(key=key, cursor=int(cursor),
                                               table=table)
        if self.metrics is not None:
            self.metrics.counter("rec_ckpt_bytes").inc(int(table.nbytes))

    def get_checkpoint(self, key: str) -> Optional[MeshCheckpoint]:
        return self.checkpoints.get(key)

    def note_progress(self, key: str, chunks: int) -> None:
        """Advance the stream's consumed-chunk watermark (monotone)."""
        if int(chunks) > self._progress.get(key, 0):
            self._progress[key] = int(chunks)

    def progress(self, key: str) -> int:
        return self._progress.get(key, 0)

    def restore_replayed(self, key: str, cursor: int) -> int:
        """Chunks the resume at `cursor` re-covers (the failed attempt
        had consumed up to the watermark): counted into
        rec_chunks_replayed so the bounded-replay proof — at most
        checkpoint.everyChunks chunks per mesh recovery — is a metric,
        not an inference."""
        replayed = max(0, self.progress(key) - int(cursor))
        if replayed and self.metrics is not None:
            self.metrics.counter("rec_chunks_replayed").inc(replayed)
        return replayed

    def release(self) -> None:
        """Drop retained stage outputs (device batches) and checkpoint
        tables when the execution finishes — the memo exists to span
        recovery loops, not executions."""
        self._memo.clear()
        self.checkpoints.clear()
        self._progress.clear()

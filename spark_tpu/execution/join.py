"""Equi-join kernel: sorted-build + binary-search probe.

Replaces the reference's hash join tier (`HashedRelation.scala:41`,
`BroadcastHashJoinExec.scala:40`, `ShuffledHashJoinExec.scala:37`) with a
sort+searchsorted formulation that XLA maps well onto TPU: the build side
is sorted once (`lax.sort`), each probe key binary-searches
(`jnp.searchsorted`), and matched build rows are gathered. O((m+n) log n)
with fully static shapes.

This kernel requires *unique* build-side keys (the FK-join case: every
TPC-H join probes a primary key). Duplicate build keys are detected on
device and surfaced as a `dup_detected` flag the executor checks —
many-to-many joins are planned to expand via a different strategy
(SURVEY.md section 7, "hard parts").
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column
from ..expr import Vec


def build_sorted(key: Vec, sel) -> Tuple:
    """Sort build side by key; invalid rows pushed to the end.

    Returns (sorted_keys, perm, num_valid, dup_detected)."""
    cap = key.data.shape[0]
    invalid = jnp.zeros((cap,), jnp.int8)
    if sel is not None:
        invalid = (~sel).astype(jnp.int8)
    if key.validity is not None:
        invalid = invalid | (~key.validity).astype(jnp.int8)
    perm0 = jnp.arange(cap, dtype=jnp.int32)
    inv_s, keys_s, perm = jax.lax.sort((invalid, key.data, perm0), num_keys=2)
    valid_s = inv_s == 0
    n_valid = jnp.sum(valid_s.astype(jnp.int32))
    # invalid slots carry arbitrary keys after the valid prefix; overwrite
    # with +max so the array is globally sorted for binary search
    if jnp.issubdtype(keys_s.dtype, jnp.floating):
        sentinel = jnp.asarray(np.inf, keys_s.dtype)
    else:
        sentinel = jnp.asarray(np.iinfo(np.dtype(keys_s.dtype)).max, keys_s.dtype)
    keys_s = jnp.where(valid_s, keys_s, sentinel)
    adj_dup = (keys_s[1:] == keys_s[:-1]) & valid_s[1:] & valid_s[:-1]
    dup = jnp.any(adj_dup)
    return keys_s, perm, n_valid, valid_s, dup


def probe(sorted_keys, perm, n_valid, probe_key: Vec, probe_sel):
    """Binary-search probe. Returns (match_idx into build batch, found mask)."""
    pos = jnp.searchsorted(sorted_keys, probe_key.data)
    pos_c = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    hit_key = jnp.take(sorted_keys, pos_c)
    found = (pos < n_valid) & (hit_key == probe_key.data)
    if probe_key.validity is not None:
        found = found & probe_key.validity
    if probe_sel is not None:
        found = found & probe_sel
    match_idx = jnp.take(perm, pos_c)
    return match_idx, found


def gather_build_columns(build: Batch, match_idx, found,
                         name_map: List[Tuple[str, str]]) -> List[Tuple[str, Column]]:
    """Gather build-side columns at match_idx; validity &= found."""
    out = []
    for src_name, out_name in name_map:
        col = build.columns[src_name]
        data = jnp.take(col.data, match_idx)
        if col.validity is not None:
            validity = jnp.take(col.validity, match_idx) & found
        else:
            validity = found
        out.append((out_name, Column(data, col.dtype, validity, col.dictionary)))
    return out

"""Equi-join kernels: sorted-build binary-search with many-to-many expansion.

Replaces the reference's join tier (`SortMergeJoinExec.scala:36`,
`HashedRelation.scala:41`, `BroadcastHashJoinExec.scala:40`,
`ShuffledHashJoinExec.scala:37`) with a sort+searchsorted formulation that
XLA maps well onto TPU:

- the build side is sorted once (`lax.sort`);
- each probe key binary-searches its match *range* [lo, hi)
  (`jnp.searchsorted` left/right), so duplicate build keys are handled;
- output rows are produced by prefix-sum expansion into a statically
  shaped output: out row r maps back to probe row p via a second
  searchsorted over the row-offset array, and to build row lo[p]+(r-off[p]).

Output capacity is a static trace-time parameter. The executor seeds it
with the probe capacity (exact for FK joins, the TPC-H shape) and, when
the traced total exceeds it, reads the real total from a metric and
re-jits with a sufficient capacity — the host-side stats->re-plan loop of
the reference's AQE (`AdaptiveSparkPlanExec.scala:64`) in miniature.

All shapes are static; everything fuses into the enclosing stage.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column
from ..expr import Vec


def canon_key_data(data):
    """One representative per join-equal float key class: -0.0 -> +0.0
    and every NaN payload -> the canonical NaN. Join keys compare NaN
    equal to NaN (the reference's/pandas semantics), so the sort total
    order (NaN greatest), searchsorted tie-breaking and `==` must all
    see a single bit pattern per class — applied to BOTH sides before
    any sort/search/hash. Non-float keys pass through untouched."""
    if not jnp.issubdtype(data.dtype, jnp.floating):
        return data
    data = jnp.where(data == 0, jnp.zeros((), data.dtype), data)
    return jnp.where(jnp.isnan(data), jnp.asarray(np.nan, data.dtype),
                     data)


def build_sorted(key: Vec, sel) -> Tuple:
    """Sort build side by key; invalid rows pushed to the end.

    Returns (sorted_keys, perm, num_valid, valid_mask_sorted)."""
    from ..testing import faults
    faults.fire("join_build")  # chaos seam: fires at trace time
    cap = key.data.shape[0]
    invalid = jnp.zeros((cap,), jnp.int8)
    if sel is not None:
        invalid = (~sel).astype(jnp.int8)
    if key.validity is not None:
        invalid = invalid | (~key.validity).astype(jnp.int8)
    perm0 = jnp.arange(cap, dtype=jnp.int32)
    inv_s, keys_s, perm = jax.lax.sort(
        (invalid, canon_key_data(key.data), perm0), num_keys=2)
    valid_s = inv_s == 0
    n_valid = jnp.sum(valid_s.astype(jnp.int32))
    # invalid slots carry arbitrary keys after the valid prefix;
    # overwrite with the sort order's +max so the array stays globally
    # sorted for binary search. For floats that is the canonical NaN
    # (valid NaN keys sort ABOVE +inf, so an inf sentinel would break
    # the order whenever the build has NaN keys and padding); sentinel
    # runs merging into a valid NaN run is fine — match ranges clip at
    # n_valid, exactly as they already do for valid +inf keys.
    if jnp.issubdtype(keys_s.dtype, jnp.floating):
        sentinel = jnp.asarray(np.nan, keys_s.dtype)
    else:
        sentinel = jnp.asarray(np.iinfo(np.dtype(keys_s.dtype)).max, keys_s.dtype)
    keys_s = jnp.where(valid_s, keys_s, sentinel)
    return keys_s, perm, n_valid, valid_s


def build_has_duplicates(sorted_keys, valid_sorted):
    """Traced bool: any two valid build rows share a key (adjacent
    check on the sorted keys). Drives the unique-build fast path's
    AQE fallback flag — a table-level property, conservatively True if
    ANY key repeats (even unmatched ones). NaN groups with NaN, as it
    does everywhere join keys compare (`==` alone would let duplicate
    NaN build keys slip past the many-to-many fallback and silently
    drop their extra matches)."""
    same = sorted_keys[1:] == sorted_keys[:-1]
    if jnp.issubdtype(sorted_keys.dtype, jnp.floating):
        same = same | (jnp.isnan(sorted_keys[1:])
                       & jnp.isnan(sorted_keys[:-1]))
    both = valid_sorted[1:] & valid_sorted[:-1]
    return jnp.any(same & both)


def match_unique(sorted_keys, n_valid, perm, probe_key: Vec, probe_sel):
    """Unique-build match: each probe row matches at most one build row
    (the FK->PK shape; reference: HashedRelation.scala keyIsUnique).
    ONE searchsorted + one build-sized gather; no expansion, no
    reindexing — probe columns pass through untouched.

    Returns (build_idx, found)."""
    pk = canon_key_data(probe_key.data)
    lo = jnp.searchsorted(sorted_keys, pk, side="left", method="sort")
    lo = jnp.minimum(lo, sorted_keys.shape[0] - 1).astype(jnp.int32)
    hit = jnp.take(sorted_keys, lo)
    eq = hit == pk
    if jnp.issubdtype(sorted_keys.dtype, jnp.floating):
        # NaN keys join equal (the reference's NaN semantics): both
        # sides are canonicalized, so `lo` lands on the build's NaN run
        # and only the `NaN == NaN` comparison itself needs the assist
        eq = eq | (jnp.isnan(hit) & jnp.isnan(pk))
    found = eq & (lo < n_valid)
    if probe_key.validity is not None:
        found = found & probe_key.validity
    if probe_sel is not None:
        found = found & probe_sel
    build_idx = jnp.take(perm, lo)
    return build_idx, found


def match_ranges(sorted_keys, n_valid, probe_key: Vec, probe_sel):
    """Binary-search each probe key's build match range.

    Returns (lo, cnt): build rows [lo, lo+cnt) in sorted order match.
    cnt is 0 for unmatched/invalid/unselected probe rows.

    method='sort' matters on TPU: the default 'scan' binary search is
    log2(build) SEQUENTIAL whole-probe gathers (~1.4s for 8M probes,
    measured), while one extra lax.sort is ~100ms."""
    pk = canon_key_data(probe_key.data)
    lo = jnp.searchsorted(sorted_keys, pk, side="left", method="sort")
    hi = jnp.searchsorted(sorted_keys, pk, side="right", method="sort")
    lo = jnp.minimum(lo, n_valid).astype(jnp.int32)
    hi = jnp.minimum(hi, n_valid).astype(jnp.int32)
    found = hi > lo
    if probe_key.validity is not None:
        found = found & probe_key.validity
    if probe_sel is not None:
        found = found & probe_sel
    cnt = jnp.where(found, hi - lo, 0).astype(jnp.int32)
    return lo, cnt


def expand(lo, cnt_key, cnt_eff, perm, out_cap: int):
    """Prefix-sum expansion of match ranges into a static-capacity output.

    cnt_key[p] = number of key-matched build rows for probe row p;
    cnt_eff[p] = rows to emit for p (== cnt_key, or max(cnt_key,1) for
    outer joins that null-extend unmatched probe rows).

    Returns (p, build_idx, is_pair, valid, total):
      p[r]        probe row of output row r
      build_idx[r] build row (meaningful when is_pair[r])
      is_pair[r]  r is a key-matched pair (False => null-extension row)
      valid[r]    r < total emitted rows
      total       traced scalar: rows actually produced (host checks
                  against out_cap and re-jits on overflow)
    """
    cap = cnt_eff.shape[0]
    assert cap < (1 << 30) and perm.shape[0] < (1 << 30), \
        "expand packs (probe idx, lo) into one int64"
    off = jnp.cumsum(cnt_eff) - cnt_eff  # exclusive prefix sum
    total = off[-1] + cnt_eff[-1]
    r = jnp.arange(out_cap, dtype=jnp.int32)
    # Each emitting probe row owns a contiguous run of output rows
    # starting at off[p]; probe indices increase across runs. Pack
    # (probe idx, lo, cnt_key==0) into one int64, scatter it at each
    # run start (non-colliding) and forward-fill with a running max —
    # gathers (take(off/lo/cnt_key, p)) are ~10x slower than scans on
    # TPU and dominated the round-3 join profile (~1.7s of Q5).
    emitting = cnt_eff > 0
    pidx = jnp.arange(cap, dtype=jnp.int64)
    zflag = (cnt_key == 0).astype(jnp.int64)
    pack = (pidx << 32) | (lo.astype(jnp.int64) << 1) | zflag
    tgt = jnp.where(emitting, off, out_cap)
    packs = jnp.zeros((out_cap,), jnp.int64).at[tgt].set(pack, mode="drop")
    offm = jnp.zeros((out_cap,), jnp.int32).at[tgt].set(
        off.astype(jnp.int32), mode="drop")
    fill = jax.lax.cummax(packs)
    off_run = jax.lax.cummax(offm)  # start position of r's run
    p = (fill >> 32).astype(jnp.int32)
    lo_p = ((fill >> 1) & jnp.int64(0x3FFFFFFF)).astype(jnp.int32)
    j = r - off_run
    # j < cnt_key[p] <=> the run emits pairs (cnt_eff==cnt_key) and not
    # the cnt_key==0 null-extension run (cnt_eff=1, one row with j=0)
    is_pair = (fill & 1) == 0
    build_pos = jnp.clip(lo_p + j, 0, perm.shape[0] - 1)
    build_idx = jnp.take(perm, build_pos)
    valid = r < total
    return p, build_idx, is_pair & valid, valid, total


class RuntimeFilter:
    """A built runtime join filter: Bloom membership over hashed int64
    keys, plus [lo, hi] value bounds when the key dtype is ordered
    (numeric/date/timestamp/decimal) — the cheap range rejection that
    needs two compares instead of k hash probes."""

    def __init__(self, bloom, lo=None, hi=None):
        self.bloom = bloom
        self.lo = lo
        self.hi = hi


def _runtime_filter_key(vec: Vec):
    """(hashed int64 values, validity, ordered) for filter build/probe.

    Dictionary strings map through the per-dictionary VALUE hashes the
    shuffle uses, so build and probe sides with independently-built
    dictionaries hash equal strings equally (codes alone would not).
    `ordered` marks dtypes whose raw values support min/max bounds."""
    if vec.dictionary is not None:
        from ..parallel.shuffle import _dict_value_hashes
        table = _dict_value_hashes(vec.dictionary)
        if table.shape[0] == 0:
            # all-NULL / zero-row string column: a 0-entry dictionary
            # has nothing to take from; validity already masks every
            # row, so any constant hash is correct
            return jnp.zeros(vec.data.shape, jnp.int64), vec.validity, \
                False
        idx = jnp.clip(vec.data.astype(jnp.int32), 0, table.shape[0] - 1)
        return jnp.take(table, idx), vec.validity, False
    ordered = not isinstance(vec.dtype, (T.StringType, T.BooleanType))
    return vec.data.astype(jnp.int64), vec.validity, ordered


def build_runtime_filter(build_batch: Batch, key_expr, ctx,
                         expected_items: int, fpp: float = 0.03
                         ) -> RuntimeFilter:
    """Build a RuntimeFilter from the build-side key column. NULL keys
    are excluded (they never equi-match). Inside shard_map the per-shard
    Bloom bits pmax-combine (bitwise OR over the one-bit-per-byte
    layout) and the bounds pmin/pmax, so the filter covers every
    shard's build rows while staying replicated."""
    from ..sketch import BloomFilter
    vec = key_expr.eval(build_batch)
    hashed, validity, ordered = _runtime_filter_key(vec)
    mask = build_batch.selection_mask()
    if validity is not None:
        mask = mask & validity
    bloom = BloomFilter.build(hashed, expected_items=expected_items,
                              fpp=fpp, mask=mask)
    lo = hi = None
    if ordered:
        raw = vec.data
        bmask = mask
        if jnp.issubdtype(raw.dtype, jnp.floating):
            pos = jnp.asarray(np.inf, raw.dtype)
            neg = jnp.asarray(-np.inf, raw.dtype)
            # a valid NaN build key would poison the bounds (NaN
            # propagates through min/max and every probe compare goes
            # False — an empty join). NaN never equi-matches anyway
            # (IEEE), so exclude it from the bounds; NaN probe keys
            # fail the range compare and prune, consistently with the
            # join's own equality.
            bmask = bmask & ~jnp.isnan(raw)
        else:
            info = np.iinfo(np.dtype(raw.dtype))
            pos = jnp.asarray(info.max, raw.dtype)
            neg = jnp.asarray(info.min, raw.dtype)
        lo = jnp.min(jnp.where(bmask, raw, pos))
        hi = jnp.max(jnp.where(bmask, raw, neg))
    if ctx.axis_name is not None and ctx.n_shards > 1:
        bloom = BloomFilter(jax.lax.pmax(bloom.bits, ctx.axis_name),
                            bloom.num_hashes)
        if lo is not None:
            lo = jax.lax.pmin(lo, ctx.axis_name)
            hi = jax.lax.pmax(hi, ctx.axis_name)
    return RuntimeFilter(bloom, lo, hi)


def apply_runtime_filter(filt: RuntimeFilter, probe_batch: Batch,
                         key_expr):
    """Per-probe-row keep mask: False is a definite non-match (prune),
    True is probabilistic (the join still decides). NULL probe keys are
    pruned — an equi-join never matches them."""
    vec = key_expr.eval(probe_batch)
    hashed, validity, ordered = _runtime_filter_key(vec)
    keep = filt.bloom.might_contain(hashed)
    if filt.lo is not None and ordered:
        # range rejection on raw values: an empty build side leaves
        # lo > hi (the sentinels), which prunes everything — correct
        # for inner/semi joins
        keep = keep & (vec.data >= filt.lo) & (vec.data <= filt.hi)
    if validity is not None:
        keep = keep & validity
    return keep


def gather_columns(batch: Batch, idx, present,
                   name_map: Sequence[Tuple[str, str]]
                   ) -> List[Tuple[str, Column]]:
    """Gather columns at idx; validity &= present (rows where the side
    contributes no value — null-extensions — become NULL).

    Columns carrying provenance compose indices (``base[idx0[idx]]``)
    instead of gathering already-gathered data: the index composition is
    ONE gather shared by every column from the same origin (XLA CSE),
    and the upstream per-column gathers die by DCE unless something else
    consumes them. This is what makes a chain of N joins cost one
    payload gather per column instead of N (Q5's profile was dominated
    by per-join payload gathers)."""
    out = []
    for src_name, out_name in name_map:
        col = batch.columns[src_name]
        if col.prov is not None:
            base_data, base_valid, idx0, present0 = col.prov
            idx2 = jnp.take(idx0, idx)
            data = jnp.take(base_data, idx2)
            new_present = present if present0 is None else \
                (jnp.take(present0, idx) & present)
            if base_valid is not None:
                validity = jnp.take(base_valid, idx2) & new_present
            else:
                validity = new_present
            out.append((out_name, Column(
                data, col.dtype, validity, col.dictionary,
                prov=(base_data, base_valid, idx2, new_present))))
            continue
        data = jnp.take(col.data, idx)
        if col.validity is not None:
            validity = jnp.take(col.validity, idx) & present
        else:
            validity = present
        out.append((out_name, Column(data, col.dtype, validity,
                                     col.dictionary,
                                     prov=(col.data, col.validity, idx,
                                           present))))
    return out

"""Sort kernel: multi-key `lax.sort` with permutation payload.

Replaces the reference's Tungsten sort tier (`SortExec.scala:40`,
`UnsafeExternalSorter.java`, `RadixSort.java`): XLA's `lax.sort` is the
device sort; there is no spill tier because batches are HBM-resident and
statically shaped. Orders follow Spark semantics: ASC -> NULLS FIRST,
DESC -> NULLS LAST by default; DESC on strings sorts by host-computed
dictionary rank (a static lookup table), since codes are not ordered.
Unselected rows sort to the end, so a sort also compacts the selection.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as T
from ..columnar import Batch, Column
from ..expr import SortOrder, Vec


def _rank_table(dictionary: pa.Array):
    """code -> lexicographic rank, computed once on host (static)."""
    order = pc.array_sort_indices(dictionary)
    ranks = np.empty(len(dictionary), dtype=np.int32)
    ranks[order.to_numpy(zero_copy_only=False)] = np.arange(
        len(dictionary), dtype=np.int32)
    return jnp.asarray(ranks)


def sort_key_operand(vec: Vec, ascending: bool):
    """Map a key column to an ascending-sortable operand of its dtype."""
    data = vec.data
    if isinstance(vec.dtype, T.StringType):
        if vec.dictionary is None:
            raise ValueError("sort on string requires dictionary")
        table = _rank_table(vec.dictionary)
        if len(table) == 0:
            # all-null column: every row is masked by the null-rank
            # operand, so any constant key works
            data = jnp.zeros(data.shape, dtype=jnp.int32)
        else:
            data = jnp.take(table, jnp.clip(data, 0, len(table) - 1))
    if isinstance(vec.dtype, T.BooleanType):
        data = data.astype(jnp.int8)
    if not ascending:
        if jnp.issubdtype(data.dtype, jnp.floating):
            data = -data
        else:
            data = ~data  # bitwise complement reverses integer order, no overflow
    return data


def sort_operands(batch: Batch, orders: Sequence[SortOrder]) -> List:
    """Ascending-comparable operand arrays for the sort keys (null-rank
    int8 columns interleaved before nullable keys). Comparing two rows'
    operand tuples lexicographically == comparing them under `orders` —
    shared by the local sort and the range-partitioning exchange."""
    operands = []
    for o in orders:
        vec = o.eval(batch)
        if vec.validity is not None:
            nulls = (~vec.validity).astype(jnp.int8)
            # ASC+NULLS FIRST: null rank 0; NULLS LAST: null rank 1
            rank = nulls if not o.nulls_first else (1 - nulls)
            operands.append(rank.astype(jnp.int8))
        operands.append(sort_key_operand(vec, o.ascending))
    return operands


def sort_permutation(batch: Batch, orders: Sequence[SortOrder]):
    """Returns (perm, num_valid): perm puts rows in order with unselected
    rows last; gathering all columns by perm and selecting iota<num_valid
    yields the sorted, compacted batch."""
    cap = batch.capacity
    sel = batch.selection
    invalid = jnp.zeros((cap,), jnp.int8) if sel is None else (~sel).astype(jnp.int8)
    operands = [invalid] + sort_operands(batch, orders)
    num_keys = len(operands)
    operands.append(jnp.arange(cap, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    n_valid = jnp.sum((sorted_ops[0] == 0).astype(jnp.int32))
    return perm, n_valid


def apply_permutation(batch: Batch, perm, n_valid) -> Batch:
    cols = {}
    for name, col in batch.columns.items():
        if col.offsets is not None:
            cols[name] = _permute_list_column(col, perm)
            continue
        data = jnp.take(col.data, perm)
        validity = None if col.validity is None else jnp.take(col.validity, perm)
        cols[name] = Column(data, col.dtype, validity, col.dictionary)
    sel = jnp.arange(batch.capacity) < n_valid
    return Batch(cols, sel)


def _permute_list_column(col: Column, perm) -> Column:
    """Row-permute an offsets-encoded array column: rebuild offsets from
    the permuted row lengths, then gather each output value slot from
    its source slice — all static shapes (the flattened values array
    keeps its capacity), so arrays survive ORDER BY instead of being
    gathered as garbage scalars (code-review r5)."""
    old_off = col.offsets
    starts = jnp.take(old_off[:-1], perm)
    lengths = jnp.take(old_off[1:] - old_off[:-1], perm)
    new_off = jnp.concatenate(
        [jnp.zeros((1,), old_off.dtype), jnp.cumsum(lengths)]) \
        .astype(old_off.dtype)
    vcap = col.data.shape[0]
    iota = jnp.arange(vcap, dtype=jnp.int32)
    out_row = jnp.clip(
        jnp.searchsorted(new_off, iota, side="right") - 1, 0,
        len(lengths) - 1)
    intra = iota - jnp.take(new_off, out_row)
    src = jnp.clip(jnp.take(starts, out_row) + intra, 0, vcap - 1)
    data = jnp.take(col.data, src)
    ev = None if col.elem_validity is None else \
        jnp.take(col.elem_validity, src)
    validity = None if col.validity is None else \
        jnp.take(col.validity, perm)
    return Column(data, col.dtype, validity, col.dictionary,
                  offsets=new_off, elem_validity=ev)

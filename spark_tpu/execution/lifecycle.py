"""Query lifecycle control: cooperative cancellation + end-to-end
deadlines.

The reference can KILL work: `SparkContext.cancelJobGroup` /
`cancelStage` propagate interrupts down to running tasks, and every
scheduler wait is interruptible, so a runaway query cannot hold the
cluster. An XLA engine has no task boundaries to interrupt — a
dispatched stage runs to completion — but it does own a set of HOST
boundaries: chunk loops, stage-attempt entries, retry backoffs,
admission-queue and arbiter-lease waits, streaming trigger
iterations. This module plants ONE cooperative token at those
boundaries:

- ``CancelToken`` — a thread-safe cancel flag plus an optional
  monotonic deadline (``spark_tpu.execution.queryDeadlineMs``). It is
  ContextVar-installed per query execution (the ShardStreamTelemetry
  pattern), so the deep drivers need no signature changes.
- ``checkpoint(where)`` — the boundary call: fires the ``cancel_point``
  chaos seam, then raises ``QueryCancelledError`` /
  ``QueryDeadlineError`` when the installed token says stop. Wired at
  chunk boundaries (ChunkRetrier), stage-attempt entry
  (_execute_recover), compile entry, scan ingest, retry-backoff entry
  (RetryPolicy), admission queue waits, arbiter lease waits, and the
  streaming trigger loop.
- ``sleep(seconds)`` — the interruptible replacement for every
  ``time.sleep`` on a cancellable path (RetryPolicy backoff, the
  ``slow`` chaos fault): wakes immediately on cancel, caps at the
  remaining deadline budget, and raises the structured error instead
  of returning into a dead query.
- ``wait_slice(remaining_s)`` — condition-variable wait capping: with
  a token installed, cv waits (admission queue, arbiter lease pool)
  wait in short slices bounded by the remaining deadline budget so
  cancellation lands within ~one poll interval instead of after
  queueTimeoutMs.

Both errors classify as ``FailureClass.CANCELLED``
(execution/failures.py): the recovery ladder re-raises them
immediately — a deadline blown mid-recovery stops the ladder, it does
not retry through it.

Token registry: ``enter_query_scope`` (called by the executor at every
execute_batch / collect entry) registers the token under
``(app_id, query_id)`` so ``session.cancel(query_id)`` can reach a
query running on another thread; the SQL service keeps its own map
keyed by service query id for ``DELETE /queries/<id>``. A nested
execution (scalar subquery, cached-subtree materialization) shares the
outer token, so cancelling the outer query stops its subqueries too.

The hard contract (chaos-proven by the cancel-point matrix in
tests/test_lifecycle.py): a cancelled/deadlined query releases every
resource it holds — arbiter leases drained, prefetch workers joined,
mesh/stream checkpoints left committed, no daemon outliving the query
— and an identical query run immediately after is byte-identical to an
uninterrupted run.
"""

from __future__ import annotations

import threading
import time
from contextvars import ContextVar
from typing import Dict, Optional, Tuple

from ..testing import faults

DEADLINE_KEY = "spark_tpu.execution.queryDeadlineMs"


class QueryCancelledError(RuntimeError):
    """The query was cancelled (session.cancel / DELETE /queries/<id>)
    and stopped at the next cooperative boundary."""

    code = "QUERY_CANCELLED"


class QueryDeadlineError(RuntimeError):
    """The query exceeded its end-to-end deadline
    (spark_tpu.execution.queryDeadlineMs). Distinct from the per-stage
    TIMEOUT class: a blown deadline stops the recovery ladder instead
    of retrying through it."""

    code = "QUERY_DEADLINE_EXCEEDED"


class CancelToken:
    """Thread-safe cancel flag + optional monotonic deadline. `cancel()`
    may be called from any thread (HTTP handler, another session);
    `check()` runs on the query thread at every cooperative boundary."""

    def __init__(self, deadline_ms: Optional[float] = None):
        self._event = threading.Event()
        self.deadline_ms = float(deadline_ms) if deadline_ms else None
        self.deadline = (time.monotonic() + self.deadline_ms / 1e3
                         if self.deadline_ms else None)

    def cancel(self) -> None:
        """Idempotent: the query stops at its next boundary; waiters
        parked in `wait()` wake immediately."""
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def remaining_s(self) -> Optional[float]:
        """Deadline budget left (negative = blown); None = no deadline."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        rem = self.remaining_s()
        return rem is not None and rem <= 0

    def check(self, where: str = "") -> None:
        """Raise the structured error when this query must stop."""
        at = f" at {where}" if where else ""
        if self._event.is_set():
            raise QueryCancelledError(f"query cancelled{at}")
        if self.expired():
            raise QueryDeadlineError(
                f"query exceeded queryDeadlineMs="
                f"{self.deadline_ms:g}{at}")

    def wait(self, seconds: float) -> None:
        """Interruptible bounded sleep: wakes on cancel, caps at the
        remaining deadline budget, raises on either. A capped wait
        raises QueryDeadlineError — the caller's full sleep would have
        outrun the budget, so sleeping the remainder then resuming
        work would just blow the deadline one boundary later."""
        s = max(0.0, float(seconds))
        rem = self.remaining_s()
        capped = rem is not None and rem < s
        if capped:
            s = max(rem, 0.0)
        if s > 0:
            self._event.wait(s)
        if self._event.is_set():
            raise QueryCancelledError("query cancelled during wait")
        if capped or self.expired():
            raise QueryDeadlineError(
                f"query exceeded queryDeadlineMs={self.deadline_ms:g} "
                f"during wait")


#: the token of the query execution running in the current context;
#: installed by the executor (or the SQL service, one layer out so
#: admission/session waits count against the deadline too)
_TOKEN: ContextVar[Optional[CancelToken]] = ContextVar(
    "spark_tpu_cancel_token", default=None)


def install(token: CancelToken):
    """Install `token` for the current context; returns the ContextVar
    reset token for `uninstall`."""
    return _TOKEN.set(token)


def uninstall(ctx_token) -> None:
    _TOKEN.reset(ctx_token)


def current_token() -> Optional[CancelToken]:
    return _TOKEN.get()


def checkpoint(where: str = "") -> None:
    """The cooperative boundary: fire the `cancel_point` chaos seam
    (the cancel-matrix delivery vehicle — a `cancel_point:cancel:n`
    rule cancels the installed token at the nth boundary), then raise
    if the installed token says stop. One None check when idle — cheap
    enough for chunk loops."""
    faults.fire("cancel_point")
    tok = _TOKEN.get()
    if tok is not None:
        tok.check(where)


def sleep(seconds: float) -> None:
    """Interruptible sleep for cancellable paths (RetryPolicy backoff,
    the `slow` chaos fault): plain time.sleep without a token."""
    tok = _TOKEN.get()
    if tok is None:
        time.sleep(seconds)
    else:
        tok.wait(seconds)


def wait_slice(remaining_s: Optional[float],
               poll_s: float = 0.05) -> Optional[float]:
    """Cap one condition-variable wait: without a token, the caller's
    own remaining timeout (None = wait forever); with one, a short
    poll slice additionally bounded by the remaining deadline budget,
    so the caller's wait loop re-runs `checkpoint()` within ~poll_s of
    a cancel and never sleeps past the deadline."""
    tok = _TOKEN.get()
    if tok is None:
        return remaining_s
    s = poll_s
    if remaining_s is not None:
        s = min(s, remaining_s)
    rem = tok.remaining_s()
    if rem is not None:
        s = min(s, max(rem, 0.0))
    return max(s, 1e-3)


# ---------------------------------------------------------------------------
# Token registry: session.cancel(query_id) -> the token of a query
# running on another thread
# ---------------------------------------------------------------------------

_TOKENS: Dict[Tuple[str, int], CancelToken] = {}
_TOKENS_LOCK = threading.Lock()


def enter_query_scope(app_id: str, query_id: int, conf):
    """Open the lifecycle scope for a query execution: install a fresh
    token (deadline armed from queryDeadlineMs) unless an outer scope —
    the SQL service, or an enclosing execution — already installed one,
    and register it for session.cancel. Returns an opaque scope for
    `exit_query_scope`."""
    tok = _TOKEN.get()
    created = None
    if tok is None:
        ms = float(conf.get(DEADLINE_KEY))
        tok = CancelToken(deadline_ms=ms if ms > 0 else None)
        created = _TOKEN.set(tok)
    key = (app_id, int(query_id))
    with _TOKENS_LOCK:
        # a nested scope under the same key (collect() wraps
        # execute_batch with the same query_id) must not claim the
        # registration: the OUTER scope's exit owns the pop, so the
        # query stays cancellable through the whole outer scope (e.g.
        # the result's device->host transfer after execute_batch)
        inserted = key not in _TOKENS
        if inserted:
            _TOKENS[key] = tok
    return (key, created, inserted)


def exit_query_scope(scope) -> None:
    if scope is None:
        return
    key, created, inserted = scope
    if inserted:
        with _TOKENS_LOCK:
            _TOKENS.pop(key, None)
    if created is not None:
        _TOKEN.reset(created)


def cancel(app_id: str, query_id: int) -> bool:
    """Cancel the identified running query (the session.cancel seat).
    Returns False when no such execution is registered (already
    finished, or never started)."""
    with _TOKENS_LOCK:
        tok = _TOKENS.get((app_id, int(query_id)))
    if tok is None:
        return False
    tok.cancel()
    return True


def cancel_current() -> None:
    """Cancel the token installed in this context — the `cancel` chaos
    fault's effect (testing/faults.py): the next checkpoint raises."""
    tok = _TOKEN.get()
    if tok is not None:
        tok.cancel()

"""Pallas dense group-by reduction kernels (MXU one-hot matmul).

XLA's scatter-add lowers colliding updates catastrophically on TPU
(~11M rows/s measured for 16M rows into 100 slots); these kernels
replace it for the dense-domain aggregate path — the role Tungsten's
`UnsafeFixedWidthAggregationMap.java:39`/`BytesToBytesMap.java` hash loop
plays on CPU in the reference.

Small domains (<= 512 columns): per-group sums are `limbs @ onehot(idx)`
with the one-hot tile living only in VMEM ([T, D] bf16) and the
contraction on the MXU.

Large domains (up to ~2^20): building a [T, D] one-hot costs D VPU ops
per ROW — the round-3 profiling showed that construction, not the
matmul, capped the 65,536-group benchmark at ~2M rows/s. The factorized
kernel instead decomposes idx = a*dB + b and uses
``onehot_D(idx) = onehot_dA(a) (x) onehot_dB(b)``:
``G[a, b] = sum_t (A[t, a] * limb[t]) * B[t, b]`` — an [dA, T] @ [T, dB]
MXU contraction per limb row whose one-hot build cost is dA+dB (~512)
instead of D (~65,536) comparisons per row.

Exactness: int64 contributions are split into 8-bit limbs (exact in
bf16) over uint32 halves; a super-tile accumulates S*T rows with
per-limb partials <= S*T*255 < 2^24, exact in the f32 MXU accumulator;
super-tile partials are summed in int64 and limb sums recombined mod
2^64 — bit-exact int64 arithmetic at MXU speed. Rows whose values are
statically bounded (counts: AccSpec.width) carry only the limbs their
width needs — the bench shape's [count, sum, sum_cnt] needs 10 limb
rows instead of 24. float64 contributions ride as Kahan-compensated
(hi, lo) float32 pairs on the VPU (small domains; large float domains
fall back to scatter in the caller).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I0 = np.int32(0)    # index-map constants must be int32 for Mosaic
TILE = 8192          # rows per grid step (large: amortizes per-step
                     # overhead — 1024-row tiles left the MXU at ~10%
                     # on the 65k-domain shape, round-4 profiling)
SUPER = 8            # tiles per exact-f32 accumulation window
D_BLOCK = 512        # small-domain kernel: columns per block
FACTOR_B = 512       # factorized kernel: dB (lane dimension)
PARTIAL_BUDGET = 256 * 1024 * 1024  # max bytes of per-call partial sums

assert TILE * SUPER * 255 < (1 << 25)  # f32-exact window


def _limb_layout(widths: Sequence[int]) -> List[Tuple[int, int, int]]:
    """Static limb plan: (int_row, half, shift8) triples. `half` selects
    the lo (0) or hi (1) uint32 word; shift8 the byte within it. Rows
    with width w <= promise values in [0, 2^w)."""
    layout = []
    for k, w in enumerate(widths):
        n_limbs = max(1, -(-min(w, 64) // 8))
        for limb in range(n_limbs):
            half, shift8 = divmod(limb, 4)
            layout.append((k, half, shift8))
    return layout


def _split_u32(int_rows: List, widths: Sequence[int], pad_rows) -> Tuple:
    """Stack the uint32 words the layout needs: all lo words, then hi
    words for rows wider than 32 bits. Returns (u32 [W, N], word_index
    map {(row, half) -> u32 row}).

    Rows with width <= 32 skip the int64 round trip entirely (a direct
    int32 truncation is exact for them): int64 is software-emulated on
    TPU and these passes showed up at chunk scale in round-4 profiles."""
    words = []
    index = {}
    for k, r in enumerate(int_rows):
        index[(k, 0)] = len(words)
        if widths[k] <= 32:
            words.append(pad_rows(r).astype(jnp.int32))
            continue
        iv = pad_rows(r.astype(jnp.int64))
        words.append((iv & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
                     .view(jnp.int32))
        index[(k, 1)] = len(words)
        words.append((iv >> 32).astype(jnp.int32))
    return jnp.stack(words), index


def _small_kernel(*refs, n_words: int, limb_plan, n_float_rows: int,
                  d_block: int):
    """One-hot [T, D] formulation for domains <= D_BLOCK."""
    pos = 0
    idx_ref = refs[pos]; pos += 1
    words_ref = None
    floats_ref = None
    if limb_plan:
        words_ref = refs[pos]; pos += 1
    if n_float_rows:
        floats_ref = refs[pos]; pos += 1
    iout_ref = None
    fout_ref = None
    if limb_plan:
        iout_ref = refs[pos]; pos += 1
    if n_float_rows:
        fout_ref = refs[pos]; pos += 1

    t = pl.program_id(2)
    d = pl.program_id(1)
    idx = idx_ref[:]  # [T] int32; out-of-range rows never match
    col = (jax.lax.broadcasted_iota(jnp.int32, (TILE, d_block), 1)
           + d * d_block)

    if limb_plan:
        onehot_b = (idx[:, None] == col).astype(jnp.bfloat16)
        w = words_ref[:, :]  # [W, T] int32 words
        # arithmetic shift + mask extracts unsigned limbs exactly
        limbs = jnp.concatenate(
            [((w[word] >> (8 * s)) & jnp.int32(0xFF))
             .astype(jnp.float32).astype(jnp.bfloat16)[None, :]
             for (word, s) in limb_plan], axis=0)  # [R, T]
        ipart = jax.lax.dot_general(
            limbs, onehot_b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(t == 0)
        def _():
            iout_ref[0] = ipart

        @pl.when(t > 0)
        def _():
            iout_ref[0] += ipart

    if n_float_rows:
        # floats avoid the MXU (f32 matmul decomposes into lossy bf16
        # passes): VPU masked reduce keeps true f32 adds, Kahan across t
        match = idx[:, None] == col  # [T, DB] bool
        frows = []
        for r in range(n_float_rows):
            v = floats_ref[r, :]  # [T] f32
            frows.append(jnp.sum(jnp.where(match, v[:, None], 0.0), axis=0))
        fpart = jnp.stack(frows, axis=0)  # [RF, DB] f32

        @pl.when(t == 0)
        def _():
            fout_ref[0, :n_float_rows] = fpart
            fout_ref[0, n_float_rows:] = jnp.zeros_like(fpart)

        @pl.when(t > 0)
        def _():
            s = fout_ref[0, :n_float_rows]
            c = fout_ref[0, n_float_rows:]
            y = fpart - c
            tt = s + y
            fout_ref[0, n_float_rows:] = (tt - s) - y
            fout_ref[0, :n_float_rows] = tt


def _factored_kernel(ia_ref, ib_ref, words_ref, out_ref, *,
                     limb_plan, a_blk: int, d_b: int):
    """Kronecker-factorized one-hot for large domains: per limb row r,
    G_r[a, b] += sum_t (A[t, a] * limb_r[t]) * B[t, b] on the MXU.
    The a-axis is gridded in `a_blk` blocks to bound the VMEM-resident
    output slab (R * a_blk * d_b f32)."""
    a = pl.program_id(1)
    t = pl.program_id(2)
    ia = ia_ref[:]  # [T] int32 in [0, d_a) (out-of-range rows match none)
    ib = ib_ref[:]
    rows_a = (jax.lax.broadcasted_iota(jnp.int32, (TILE, a_blk), 1)
              + a * a_blk)
    rows_b = jax.lax.broadcasted_iota(jnp.int32, (TILE, d_b), 1)
    onehot_a = (ia[:, None] == rows_a).astype(jnp.bfloat16)  # [T, aB]
    onehot_b = (ib[:, None] == rows_b).astype(jnp.bfloat16)  # [T, dB]
    w = words_ref[:, :]

    parts = []
    for (word, s) in limb_plan:
        # minor-dim insertion must happen on the 32-bit value (Mosaic
        # rejects it on bf16); cast after the [T] -> [T, 1] reshape
        limb2 = ((w[word][:, None] >> (8 * s)) & jnp.int32(0xFF)) \
            .astype(jnp.float32).astype(jnp.bfloat16)  # [T, 1]
        scaled_a = onehot_a * limb2                     # [T, dA]
        g = jax.lax.dot_general(
            scaled_a, onehot_b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [dA, dB]
        parts.append(g[None])
    part = jnp.concatenate(parts, axis=0)  # [R, dA, dB]

    @pl.when(t == 0)
    def _():
        out_ref[0] = part

    @pl.when(t > 0)
    def _():
        out_ref[0] += part


def dense_groupby_sums(idx, int_rows: Sequence, float_rows: Sequence,
                       domain: int, interpret: bool = False,
                       int_widths: Optional[Sequence[int]] = None
                       ) -> Tuple[List, List]:
    """Exact per-group sums.

    idx: int32[N] in [0, domain) (out-of-range rows are dropped);
    int_rows: int64[N] contribution arrays (int_widths[k] bounds row k's
    values to [0, 2^w) — fewer limbs); float_rows: float64[N].
    Returns ([int64[domain]], [float64[domain]]).
    """
    n = idx.shape[0]
    n_i = len(int_rows)
    n_f = len(float_rows)
    widths = list(int_widths) if int_widths is not None else [64] * n_i
    assert len(widths) == n_i
    rows_per_super = TILE * SUPER
    num_super = max(1, -(-n // rows_per_super))
    n_pad = num_super * rows_per_super

    use_factored = domain > D_BLOCK and n_i > 0
    if use_factored and n_f:
        raise ValueError("float rows unsupported for large domains "
                         "(caller must fall back to scatter)")

    if use_factored:
        d_b = FACTOR_B
        d_a = -(-domain // d_b)
        d_a = -(-d_a // 8) * 8  # sublane multiple
        d_pad = d_a * d_b
    else:
        d_pad = -(-domain // 128) * 128
        d_block = min(D_BLOCK, d_pad)
        num_dblk = -(-d_pad // d_block)
        d_pad = num_dblk * d_block

    idx32 = idx.astype(jnp.int32)
    if n_pad != n:
        # padding rows get an index that matches no one-hot column
        idx32 = jnp.pad(idx32, (0, n_pad - n), constant_values=d_pad)

    def pad_rows(r):
        return jnp.pad(r, (0, n_pad - n)) if n_pad != n else r

    layout = _limb_layout(widths)
    u32 = word_index = None
    if n_i:
        u32, word_index = _split_u32(int_rows, widths, pad_rows)
    limb_plan = tuple((word_index[(k, h)], s) for (k, h, s) in layout) \
        if n_i else ()
    n_words = 0 if u32 is None else u32.shape[0]
    n_limb_rows = len(limb_plan)
    n_float_rows = 2 * n_f

    f32 = None
    if n_f:
        fv = jnp.stack([pad_rows(r.astype(jnp.float64)) for r in float_rows])
        fhi = fv.astype(jnp.float32)
        flo = (fv - fhi.astype(jnp.float64)).astype(jnp.float32)
        f32 = jnp.concatenate([fhi, flo], axis=0)  # [2*n_f, Npad]

    # per-super partial buffers scale as num_super * rows * d_pad f32;
    # chunk supers so one call's partials fit PARTIAL_BUDGET (floats
    # carry 2x rows: Kahan sums + compensations)
    bytes_per_super = (n_limb_rows + 2 * n_float_rows) * d_pad * 4
    supers_per_call = max(1, min(num_super,
                                 PARTIAL_BUDGET // max(1, bytes_per_super)))

    limb_acc = None   # [R, d_pad] int64
    float_acc = None  # [2*n_f, d_pad] f64
    start = 0
    while start < num_super:
        cs = min(supers_per_call, num_super - start)
        r0 = start * rows_per_super
        r1 = (start + cs) * rows_per_super
        idx_c = jax.lax.slice_in_dim(idx32, r0, r1)

        if use_factored:
            ia = jnp.minimum(idx_c // d_b, d_a)  # padding -> row d_a: none
            ib = idx_c % d_b
            u32_c = jax.lax.slice_in_dim(u32, r0, r1, axis=1)
            # bound the VMEM output slab to ~4MB per grid step
            a_blk = max(8, min(d_a, (4 << 20)
                               // max(1, n_limb_rows * d_b * 4)))
            a_blk = (a_blk // 8) * 8
            num_ablk = -(-d_a // a_blk)
            out = pl.pallas_call(
                functools.partial(_factored_kernel, limb_plan=limb_plan,
                                  a_blk=a_blk, d_b=d_b),
                grid=(cs, num_ablk, SUPER),
                in_specs=[
                    pl.BlockSpec((TILE,), lambda s, a, t: (s * SUPER + t,),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((TILE,), lambda s, a, t: (s * SUPER + t,),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((n_words, TILE),
                                 lambda s, a, t: (_I0, s * SUPER + t),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (1, n_limb_rows, a_blk, d_b),
                    lambda s, a, t: (s, _I0, a, _I0),
                    memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct(
                    (cs, n_limb_rows, num_ablk * a_blk, d_b), jnp.float32),
                interpret=interpret,
            )(ia, ib, u32_c)
            part = out.astype(jnp.int64).sum(axis=0) \
                .reshape(n_limb_rows, num_ablk * a_blk * d_b)[:, :d_pad]
            limb_acc = part if limb_acc is None else limb_acc + part
        else:
            operands = [idx_c]
            in_specs = [pl.BlockSpec((TILE,),
                                     lambda s, d, t: (s * SUPER + t,),
                                     memory_space=pltpu.VMEM)]
            out_shapes = []
            out_specs = []
            if n_i:
                operands.append(jax.lax.slice_in_dim(u32, r0, r1, axis=1))
                in_specs.append(pl.BlockSpec(
                    (n_words, TILE), lambda s, d, t: (_I0, s * SUPER + t),
                    memory_space=pltpu.VMEM))
                out_shapes.append(jax.ShapeDtypeStruct(
                    (cs, n_limb_rows, d_pad), jnp.float32))
                out_specs.append(pl.BlockSpec(
                    (1, n_limb_rows, d_block), lambda s, d, t: (s, _I0, d),
                    memory_space=pltpu.VMEM))
            if n_f:
                operands.append(jax.lax.slice_in_dim(f32, r0, r1, axis=1))
                in_specs.append(pl.BlockSpec(
                    (n_float_rows, TILE),
                    lambda s, d, t: (_I0, s * SUPER + t),
                    memory_space=pltpu.VMEM))
                # 2x rows: [0:RF] Kahan sums, [RF:2RF] compensations
                out_shapes.append(jax.ShapeDtypeStruct(
                    (cs, 2 * n_float_rows, d_pad), jnp.float32))
                out_specs.append(pl.BlockSpec(
                    (1, 2 * n_float_rows, d_block),
                    lambda s, d, t: (s, _I0, d),
                    memory_space=pltpu.VMEM))

            outs = pl.pallas_call(
                functools.partial(_small_kernel, n_words=n_words,
                                  limb_plan=limb_plan,
                                  n_float_rows=n_float_rows,
                                  d_block=d_block),
                grid=(cs, num_dblk, SUPER),
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                interpret=interpret,
            )(*operands)
            pos = 0
            if n_i:
                part = outs[pos].astype(jnp.int64).sum(axis=0)
                limb_acc = part if limb_acc is None else limb_acc + part
                pos += 1
            if n_f:
                fpart = outs[pos]
                sums = fpart[:, :n_float_rows].astype(jnp.float64)
                comps = fpart[:, n_float_rows:].astype(jnp.float64)
                part = (sums - comps).sum(axis=0)
                float_acc = part if float_acc is None else float_acc + part
        start += cs

    int_out: List = []
    if n_i:
        # exact int64 limb recombination per the static layout
        totals = [jnp.zeros((d_pad,), jnp.int64) for _ in range(n_i)]
        for r, (k, half, s) in enumerate(layout):
            totals[k] = totals[k] + (limb_acc[r] << (8 * s + 32 * half))
        int_out = [t[:domain] for t in totals]
    float_out: List = []
    if n_f:
        for k in range(n_f):
            float_out.append((float_acc[k] + float_acc[n_f + k])[:domain])
    return int_out, float_out

"""Pallas dense group-by reduction kernel (MXU one-hot matmul).

XLA's scatter-add lowers colliding updates catastrophically on TPU
(~11M rows/s measured for 16M rows into 100 slots); this kernel replaces
it for the dense-domain aggregate path — the role Tungsten's
`UnsafeFixedWidthAggregationMap.java:39`/`BytesToBytesMap.java` hash loop
plays on CPU in the reference.

Formulation: for group index `idx[N]` in [0, D) and contribution rows,
the per-group sums are `rows @ onehot(idx)`. The one-hot tile only ever
exists in VMEM ([T, D_BLK] bf16), and the contraction runs on the MXU.

Exactness: int64 contributions are split (outside the kernel) into two
uint32 halves, and (inside the kernel) each half into four 8-bit limbs
(exact in bf16). A super-tile accumulates S*T rows per output block with
per-limb partial sums <= S*T*255 < 2^24, i.e. exact in the f32 MXU
accumulator; super-tile partials are summed in int64 and the 8 limb sums
recombined mod 2^64 — bit-exact int64 arithmetic at MXU speed.
float64 contributions ride as (hi, lo) float32 pairs (two-float split);
the per-super-tile f32 accumulation is Kahan-compensated (a carried
compensation row per float row), and super-tile partials (sum minus
compensation) are combined in f64 — worst-case error is the within-tile
f32 tree-reduce, ~1e-8 relative, vs plain f32 running sums' 1e-6.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I0 = np.int32(0)    # index-map constants must be int32 for Mosaic
TILE = 1024          # rows per grid step
SUPER = 64           # tiles per exact-f32 accumulation window (T*S*255 < 2^24)
D_BLOCK = 512        # domain columns per block

assert TILE * SUPER * 255 < (1 << 25)  # f32-exact window (<=2^24 ulp-1 sums)


def _kernel(*refs, n_int_rows: int, n_float_rows: int, d_block: int):
    pos = 0
    idx_ref = refs[pos]; pos += 1
    ints_ref = None
    floats_ref = None
    if n_int_rows:
        ints_ref = refs[pos]; pos += 1
    if n_float_rows:
        floats_ref = refs[pos]; pos += 1
    iout_ref = None
    fout_ref = None
    if n_int_rows:
        iout_ref = refs[pos]; pos += 1
    if n_float_rows:
        fout_ref = refs[pos]; pos += 1

    t = pl.program_id(2)
    d = pl.program_id(1)
    idx = idx_ref[:]  # [T] int32; out-of-range rows never match any column
    col = (jax.lax.broadcasted_iota(jnp.int32, (TILE, d_block), 1)
           + d * d_block)

    if n_int_rows:
        onehot_b = (idx[:, None] == col).astype(jnp.bfloat16)
        u = ints_ref[:, :]  # [R, T] int32 (bit pattern of the u32 half)
        # arithmetic shift + mask extracts the same unsigned limbs as a
        # logical shift would; int32 casts are TPU-native (u32 casts aren't)
        limbs = jnp.concatenate(
            [((u >> (8 * s)) & jnp.int32(0xFF)).astype(jnp.float32)
             .astype(jnp.bfloat16)
             for s in range(4)], axis=0)  # [4R, T], limb-major
        ipart = jax.lax.dot_general(
            limbs, onehot_b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(t == 0)
        def _():
            iout_ref[0] = ipart

        @pl.when(t > 0)
        def _():
            iout_ref[0] += ipart

    if n_float_rows:
        # floats avoid the MXU (f32 matmul decomposes into lossy bf16
        # passes): VPU masked reduce keeps true f32 adds
        match = idx[:, None] == col  # [T, DB] bool
        frows = []
        for r in range(n_float_rows):
            v = floats_ref[r, :]  # [T] f32
            frows.append(jnp.sum(jnp.where(match, v[:, None], 0.0), axis=0))
        fpart = jnp.stack(frows, axis=0)  # [RF, DB] f32

        # Kahan-compensated running sum across the super-tile window:
        # rows [0:RF] carry the sum, rows [RF:2RF] the compensation, so
        # per-window error stays O(eps) instead of O(window * eps).
        @pl.when(t == 0)
        def _():
            fout_ref[0, :n_float_rows] = fpart
            fout_ref[0, n_float_rows:] = jnp.zeros_like(fpart)

        @pl.when(t > 0)
        def _():
            s = fout_ref[0, :n_float_rows]
            c = fout_ref[0, n_float_rows:]
            y = fpart - c
            tt = s + y
            fout_ref[0, n_float_rows:] = (tt - s) - y
            fout_ref[0, :n_float_rows] = tt


def dense_groupby_sums(idx, int_rows: Sequence, float_rows: Sequence,
                       domain: int, interpret: bool = False
                       ) -> Tuple[List, List]:
    """Exact per-group sums.

    idx: int32[N] in [0, domain) (out-of-range rows are dropped);
    int_rows: int64[N] contribution arrays; float_rows: float64[N].
    Returns ([int64[domain]], [float64[domain]]).
    """
    n = idx.shape[0]
    n_i = len(int_rows)
    n_f = len(float_rows)
    rows_per_super = TILE * SUPER
    num_super = max(1, -(-n // rows_per_super))
    n_pad = num_super * rows_per_super
    d_pad = -(-domain // 128) * 128
    d_block = min(D_BLOCK, d_pad)
    # the grid covers num_dblk blocks of d_block columns each; d_pad must
    # be an exact multiple or trailing columns are never written (garbage
    # on hardware, silently zero in interpret mode)
    num_dblk = -(-d_pad // d_block)
    d_pad = num_dblk * d_block

    idx32 = idx.astype(jnp.int32)
    if n_pad != n:
        # padding rows get an index that matches no one-hot column
        idx32 = jnp.pad(idx32, (0, n_pad - n), constant_values=d_pad)

    def pad_rows(r):
        return jnp.pad(r, (0, n_pad - n)) if n_pad != n else r

    n_int_rows = 2 * n_i
    n_float_rows = 2 * n_f
    operands = [idx32]
    in_specs = [pl.BlockSpec((TILE,), lambda s, d, t: (s * SUPER + t,),
                             memory_space=pltpu.VMEM)]
    out_shapes = []
    out_specs = []

    if n_i:
        iv = jnp.stack([pad_rows(r.astype(jnp.int64)) for r in int_rows])
        lo = (iv & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32) \
            .view(jnp.int32)
        hi = (iv >> 32).astype(jnp.int32)
        u32 = jnp.concatenate([lo, hi], axis=0)  # [2*n_i, Npad] int32 bits
        operands.append(u32)
        in_specs.append(pl.BlockSpec(
            (n_int_rows, TILE), lambda s, d, t: (_I0, s * SUPER + t),
            memory_space=pltpu.VMEM))
        out_shapes.append(jax.ShapeDtypeStruct(
            (num_super, 4 * n_int_rows, d_pad), jnp.float32))
        out_specs.append(pl.BlockSpec(
            (1, 4 * n_int_rows, d_block), lambda s, d, t: (s, _I0, d),
            memory_space=pltpu.VMEM))

    if n_f:
        fv = jnp.stack([pad_rows(r.astype(jnp.float64)) for r in float_rows])
        fhi = fv.astype(jnp.float32)
        flo = (fv - fhi.astype(jnp.float64)).astype(jnp.float32)
        f32 = jnp.concatenate([fhi, flo], axis=0)  # [2*n_f, Npad]
        operands.append(f32)
        in_specs.append(pl.BlockSpec(
            (n_float_rows, TILE), lambda s, d, t: (_I0, s * SUPER + t),
            memory_space=pltpu.VMEM))
        # 2x rows: [0:RF] Kahan sums, [RF:2RF] compensations
        out_shapes.append(jax.ShapeDtypeStruct(
            (num_super, 2 * n_float_rows, d_pad), jnp.float32))
        out_specs.append(pl.BlockSpec(
            (1, 2 * n_float_rows, d_block), lambda s, d, t: (s, _I0, d),
            memory_space=pltpu.VMEM))

    grid = (num_super, num_dblk, SUPER)
    kernel = functools.partial(
        _kernel, n_int_rows=n_int_rows, n_float_rows=n_float_rows,
        d_block=d_block)

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*operands)
    pos = 0
    ipart = fpart = None
    if n_i:
        ipart = outs[pos]; pos += 1
    if n_f:
        fpart = outs[pos]; pos += 1

    int_out: List = []
    if n_i:
        # [num_super, 4*2*n_i, d_pad] f32 -> exact int64 limb sums
        limb_sums = ipart.astype(jnp.int64).sum(axis=0)  # [8*n_i grouped, d]
        # rows laid out limb-major over the concatenated (lo, hi) halves:
        # limb s of half h of acc k lives at row s*(2*n_i) + h*n_i + k
        for k in range(n_i):
            total = jnp.zeros((d_pad,), jnp.int64)
            for s in range(4):
                lo_row = limb_sums[s * n_int_rows + k]
                hi_row = limb_sums[s * n_int_rows + n_i + k]
                total = total + (lo_row << (8 * s)) + (hi_row << (8 * s + 32))
            int_out.append(total[:domain])
    float_out: List = []
    if n_f:
        # Kahan state -> true window sum is s - c; combine windows in f64
        sums = fpart[:, :n_float_rows].astype(jnp.float64)
        comps = fpart[:, n_float_rows:].astype(jnp.float64)
        fs = (sums - comps).sum(axis=0)  # [2*n_f, d]
        for k in range(n_f):
            float_out.append((fs[k] + fs[n_f + k])[:domain])
    return int_out, float_out

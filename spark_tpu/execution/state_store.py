"""Incremental streaming state store: versioned deltas + snapshots.

The `RocksDBStateStoreProvider` analog scaled to this engine
(reference: `HDFSBackedStateStoreProvider.scala:73` keeps one full
state file per version; RocksDB keeps **changelog deltas** between
periodic snapshot uploads). The seed streaming loop rewrote the ENTIRE
aggregate state to disk every trigger (`_save_state` dumped every
accumulator table as one npz per batch) — O(state) I/O per trigger no
matter how few groups a micro-batch touched. This store makes
per-trigger persistence incremental:

- **delta** (the common case): only the groups whose accumulators
  changed this batch, diffed on HOST from the pre/post tables — for
  the dense-domain device path an ``__idx__`` vector of changed group
  slots plus each table's values at those slots; for the event-time
  host-table path the upserted rows plus tombstoned (evicted) keys.
- **snapshot**: the full state, written for version 0 and then every
  ``spark_tpu.streaming.stateStore.snapshotEveryDeltas`` versions
  (default 10), bounding restore replay.
- **restore**: newest snapshot <= the committed version + replay of
  the following deltas (at most snapshotEveryDeltas of them).
- **compaction**: `prune` retires snapshots and deltas older than the
  newest snapshot at-or-below the retained-version floor — never a
  file the last committed version's restore chain needs.

Durability: every file is written to a tmp name, flushed + fsync'd,
then `os.replace`d — a torn write can never shadow a committed
version. A replayed batch (crash between the offset and commit logs)
re-commits its version by atomic overwrite, so replays are idempotent.

The ``stream_state_commit`` chaos seam fires at every commit entry
(before any byte is written): an injected fault models a hard crash at
the state-persistence boundary with the previous version intact.

Layout (under the query's ``<checkpoint>/state/``)::

    deltas/delta-<version>.npz            dense-table delta
    deltas/delta-<version>.parquet        event-time upsert rows
    deltas/delta-<version>.tombstones.parquet   evicted keys (if any)
    snapshots/snapshot-<version>.{npz,parquet}  full state
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

SNAPSHOT_EVERY_KEY = "spark_tpu.streaming.stateStore.snapshotEveryDeltas"
RETAIN_KEY = "spark_tpu.streaming.retainBatches"

_FILE_RX = re.compile(
    r"^(?P<kind>delta|snapshot)-(?P<ver>\d+)"
    r"(?P<tomb>\.tombstones)?\.(?P<ext>npz|parquet)$")


def fsync_replace(tmp: str, final: str) -> None:
    """THE torn-write guard for every checkpoint surface (state files,
    metadata logs, sink parts + manifests — one definition, so crash
    behavior can't diverge between them): fsync the tmp file, then
    atomically swap it in. A lost rename is never load-bearing — the
    batch re-runs; a torn rename cannot happen (os.replace is atomic);
    a reordered flush leaves a corrupt file that the readers
    (_MetadataLog.latest / FileStreamSource.slice healing) fall back
    across."""
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, final)




class StateStore:
    """One streaming query's versioned state files. Versions are batch
    ids; exactly one delta OR snapshot file exists per committed
    version, so the restore chain `newest snapshot <= v` + deltas
    `(s, v]` is always dense."""

    def __init__(self, state_dir: str, conf, metrics=None):
        self.dir = state_dir
        self.delta_dir = os.path.join(state_dir, "deltas")
        self.snap_dir = os.path.join(state_dir, "snapshots")
        os.makedirs(self.delta_dir, exist_ok=True)
        os.makedirs(self.snap_dir, exist_ok=True)
        self.snapshot_every = max(1, int(conf.get(SNAPSHOT_EVERY_KEY)))
        self.metrics = metrics
        #: deltas replayed by the most recent load_* call (the
        #: bounded-restore proof is a readable number, not an inference)
        self.last_restore_replayed = 0

    # -- file inventory -----------------------------------------------------

    def _versions(self, d: str, kind: str) -> List[int]:
        out = []
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for name in names:
            m = _FILE_RX.match(name)
            if m and m.group("kind") == kind and not m.group("tomb"):
                out.append(int(m.group("ver")))
        return sorted(set(out))

    def snapshot_versions(self) -> List[int]:
        return self._versions(self.snap_dir, "snapshot")

    def delta_versions(self) -> List[int]:
        return self._versions(self.delta_dir, "delta")

    def kind_for(self, version: int) -> str:
        """delta or snapshot for this version — derived from the files
        on disk, so a REPLAYED version deterministically rewrites the
        same kind it originally had."""
        snaps = [v for v in self.snapshot_versions() if v < version]
        if not snaps:
            return "snapshot"
        return ("snapshot"
                if version - max(snaps) >= self.snapshot_every
                else "delta")

    def _path(self, kind: str, version: int, ext: str,
              tomb: bool = False) -> str:
        d = self.snap_dir if kind == "snapshot" else self.delta_dir
        suffix = ".tombstones" if tomb else ""
        return os.path.join(d, f"{kind}-{version}{suffix}.{ext}")

    def _fire_seam(self) -> None:
        from ..testing import faults
        faults.fire("stream_state_commit")

    def _count_bytes(self, kind: str, nbytes: int) -> None:
        if self.metrics is not None:
            name = ("streaming_state_snapshot_bytes"
                    if kind == "snapshot"
                    else "streaming_state_delta_bytes")
            self.metrics.counter(name).inc(int(nbytes))

    # -- dense-table codec (the device direct-aggregate path) ---------------

    def commit_tables(self, version: int, flat: Dict[str, np.ndarray],
                      prev: Optional[Dict[str, np.ndarray]]) -> dict:
        """Persist the host copies of the accumulator tables for
        `version`. `prev` is the committed state at `version - 1` (None
        for the first version); a delta stores only the group slots
        where any table changed. Returns {"kind", "bytes", "changed"}."""
        self._fire_seam()
        kind = self.kind_for(version)
        changed = None
        if kind == "delta":
            if prev is None:
                prev = self.load_tables(version - 1)
            payload = _diff_tables(prev, flat)
            if payload is None:  # shape drift: snapshot is the fallback
                kind = "snapshot"
            else:
                changed = int(payload["__idx__"].shape[0])
                # full-churn guard: a delta of (nearly) every group is
                # LARGER than the snapshot it avoids (values + the
                # __idx__ vector) — write the snapshot instead. The
                # decision is a pure function of (prev, post), so a
                # replayed batch deterministically re-picks it.
                delta_nbytes = sum(np.asarray(a).nbytes
                                   for a in payload.values())
                snap_nbytes = sum(np.asarray(a).nbytes
                                  for a in flat.values())
                if delta_nbytes >= snap_nbytes:
                    kind = "snapshot"
                    changed = None
        if kind == "snapshot":
            payload = dict(flat)
        path = self._path(kind, version, "npz")
        tmp = path + ".tmp.npz"
        np.savez(tmp, **payload)
        fsync_replace(tmp, path)
        nbytes = os.path.getsize(path)
        self._count_bytes(kind, nbytes)
        return {"kind": kind, "bytes": int(nbytes), "changed": changed}

    def load_tables(self, version: int
                    ) -> Optional[Dict[str, np.ndarray]]:
        """Restore the flat table dict at `version`: newest snapshot
        <= version, then replay the following deltas in order."""
        if version < 0:
            return None
        snaps = [v for v in self.snapshot_versions() if v <= version]
        if not snaps:
            raise FileNotFoundError(
                f"no state snapshot at or below version {version} "
                f"under {self.snap_dir}")
        base = max(snaps)
        with np.load(self._path("snapshot", base, "npz")) as z:
            flat = {k: np.array(z[k]) for k in z.files}
        replayed = 0
        for v in range(base + 1, version + 1):
            with np.load(self._path("delta", v, "npz")) as z:
                idx = z["__idx__"]
                for k in z.files:
                    if k == "__idx__":
                        continue
                    flat[k][idx] = z[k]
            replayed += 1
        self.last_restore_replayed = replayed
        return flat

    # -- host-frame codec (the event-time watermark path) -------------------

    def _keys_path(self) -> str:
        return os.path.join(self.dir, "frame_keys.json")

    def _save_key_cols(self, key_cols: List[str]) -> None:
        """The frame codec's key columns, persisted once: load_frame
        needs them to replay deltas (drop touched keys, append
        upserts) without the caller in hand."""
        import json
        path = self._keys_path()
        if os.path.exists(path):
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"key_cols": list(key_cols)}, f)
        fsync_replace(tmp, path)

    def _load_key_cols(self) -> Optional[List[str]]:
        import json
        try:
            with open(self._keys_path()) as f:
                return list(json.load(f)["key_cols"])
        except (OSError, ValueError, KeyError):
            return None

    def commit_frame(self, version: int, pdf: Optional[pd.DataFrame],
                     prev: Optional[pd.DataFrame],
                     key_cols: List[str]) -> dict:
        """Persist the event-time host state table for `version` as an
        upsert/tombstone delta against `prev` (the committed state at
        `version - 1`), or a full snapshot on the cadence."""
        self._fire_seam()
        self._save_key_cols(key_cols)
        post = pdf if pdf is not None else pd.DataFrame()
        kind = self.kind_for(version)
        tombs = None
        if kind == "delta":
            ups, tombs = _diff_frames(prev, post, key_cols)
            if len(post) and len(ups) >= len(post):
                # full-churn guard (row-count proxy): every row
                # upserted means the delta IS the state — snapshot
                kind = "snapshot"
                tombs = None
            else:
                payload = ups
        if kind == "snapshot":
            payload = post
        path = self._path(kind, version, "parquet")
        tmp = path + ".tmp"
        payload.to_parquet(tmp)
        fsync_replace(tmp, path)
        nbytes = os.path.getsize(path)
        tomb_path = self._path("delta", version, "parquet", tomb=True)
        if tombs is not None and len(tombs):
            ttmp = tomb_path + ".tmp"
            tombs.to_parquet(ttmp)
            fsync_replace(ttmp, tomb_path)
            nbytes += os.path.getsize(tomb_path)
        elif os.path.exists(tomb_path):
            # replay wrote fewer tombstones than a torn earlier attempt
            os.remove(tomb_path)
        self._count_bytes(kind, nbytes)
        return {"kind": kind, "bytes": int(nbytes),
                "changed": (int(len(payload)) if kind == "delta"
                            else None)}

    def load_frame(self, version: int) -> Optional[pd.DataFrame]:
        if version < 0:
            return None
        snaps = [v for v in self.snapshot_versions() if v <= version]
        if not snaps:
            raise FileNotFoundError(
                f"no state snapshot at or below version {version} "
                f"under {self.snap_dir}")
        base = max(snaps)
        state = pd.read_parquet(self._path("snapshot", base, "parquet"))
        key_cols = self._load_key_cols()
        replayed = 0
        for v in range(base + 1, version + 1):
            ups = pd.read_parquet(self._path("delta", v, "parquet"))
            tomb_path = self._path("delta", v, "parquet", tomb=True)
            tombs = (pd.read_parquet(tomb_path)
                     if os.path.exists(tomb_path) else None)
            state = _apply_frame_delta(state, ups, tombs, key_cols)
            replayed += 1
        self.last_restore_replayed = replayed
        if not len(state):
            return state if len(state.columns) else None
        return state.reset_index(drop=True)

    # -- compaction ---------------------------------------------------------

    def prune(self, committed: int, retain: int) -> None:
        """Retire files no retained version's restore chain needs:
        restoring any version v >= floor uses the newest snapshot <= v,
        which is >= the newest snapshot <= floor — so snapshots before
        it and deltas at-or-before it are dead."""
        floor = committed - int(retain)
        snaps = [v for v in self.snapshot_versions() if v <= floor]
        if not snaps:
            return
        keep = max(snaps)
        for v in self.snapshot_versions():
            if v < keep:
                for ext in ("npz", "parquet"):
                    _rm(self._path("snapshot", v, ext))
        for v in self.delta_versions():
            if v <= keep:
                for ext in ("npz", "parquet"):
                    _rm(self._path("delta", v, ext))
                _rm(self._path("delta", v, "parquet", tomb=True))


def _rm(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def _diff_tables(prev: Dict[str, np.ndarray],
                 post: Dict[str, np.ndarray]) -> Optional[dict]:
    """Changed-group delta between two flat table dicts sharing the
    group-domain leading axis. None when shapes/keys drifted (the
    caller snapshots instead). NaN-stable: an accumulator slot that
    stays NaN is NOT a change."""
    if prev is None or set(prev) != set(post):
        return None
    mask = None
    for name in sorted(post):
        a, b = np.asarray(prev[name]), np.asarray(post[name])
        if a.shape != b.shape:
            return None
        d = a != b
        if np.issubdtype(a.dtype, np.floating):
            d &= ~(np.isnan(a) & np.isnan(b))
        if d.ndim > 1:
            d = d.any(axis=tuple(range(1, d.ndim)))
        mask = d if mask is None else (mask | d)
    if mask is None:
        return None
    idx = np.nonzero(mask)[0].astype(np.int64)
    payload = {"__idx__": idx}
    for name in post:
        payload[name] = np.asarray(post[name])[idx]
    return payload


def _diff_frames(prev: Optional[pd.DataFrame], post: pd.DataFrame,
                 key_cols: List[str]):
    """(upserts, tombstone_keys) taking `prev` to `post`, both keyed
    (uniquely) by `key_cols` — new keys and changed rows upsert,
    vanished keys (watermark eviction) tombstone."""
    if prev is None or not len(prev):
        return post.reset_index(drop=True), None
    if not len(post):
        return (post.iloc[0:0].reset_index(drop=True),
                prev[key_cols].reset_index(drop=True))
    prev_i = prev.set_index(key_cols)
    post_i = post.set_index(key_cols)
    common = prev_i.index.intersection(post_i.index)
    new_keys = post_i.index.difference(prev_i.index)
    deleted = prev_i.index.difference(post_i.index)
    changed = common[:0]
    if len(common):
        a = prev_i.loc[common]
        b = post_i.loc[common]
        same = (a.values == b.values)
        # NaN == NaN is False elementwise; treat both-NaN as unchanged
        try:
            both_nan = pd.isna(a).values & pd.isna(b).values
            same = same | both_nan
        except TypeError:
            pass
        changed = common[~same.all(axis=1)]
    ups_idx = new_keys.append(changed)
    ups = post_i.loc[ups_idx].reset_index() if len(ups_idx) \
        else post.iloc[0:0]
    tombs = (prev_i.loc[deleted].reset_index()[key_cols]
             if len(deleted) else None)
    return ups.reset_index(drop=True)[list(post.columns)], tombs


def _apply_frame_delta(state: pd.DataFrame, ups: pd.DataFrame,
                       tombs: Optional[pd.DataFrame],
                       key_cols: Optional[List[str]]) -> pd.DataFrame:
    """Replay one delta: drop every touched key from `state`, then
    append the upsert rows (tombstoned keys simply stay dropped)."""
    touched = [t for t in (ups, tombs) if t is not None and len(t)]
    if not touched:
        return state
    if key_cols is None:
        # keys sidecar lost: the only safe fallback is tombstone
        # columns (they carry exactly the keys); without either the
        # delta cannot be applied
        if tombs is not None:
            key_cols = list(tombs.columns)
        else:
            raise FileNotFoundError(
                "state-store frame_keys.json missing: cannot replay "
                "event-time deltas without the key columns")
    if len(state):
        sidx = pd.MultiIndex.from_frame(state[key_cols]) \
            if len(key_cols) > 1 else pd.Index(state[key_cols[0]])
        drop = set()
        for t in touched:
            tidx = pd.MultiIndex.from_frame(t[key_cols]) \
                if len(key_cols) > 1 else pd.Index(t[key_cols[0]])
            drop.update(tidx)
        keep = ~sidx.isin(drop)
        state = state[np.asarray(keep)]
    if len(ups):
        ups = ups[list(state.columns)] if len(state.columns) else ups
        state = pd.concat([state, ups], ignore_index=True)
    return state

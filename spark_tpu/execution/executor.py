"""Query execution driver.

Mirrors the reference's `execution/QueryExecution.scala` phase pipeline
(analyzed -> optimizedPlan -> sparkPlan -> executedPlan -> toRdd), except
the terminal artifact is a single jitted stage function over columnar
Batches instead of an RDD DAG: XLA compilation replaces both Janino
whole-stage codegen and task scheduling for the single-chip path. The
compiled-stage cache keyed on the physical plan fingerprint is the analog
of `CodeGenerator.compile:1435`'s Janino cache.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar import Batch
from ..config import Conf
from ..plan import logical as L
from ..plan import physical as P
from ..plan.optimizer import default_optimizer
from ..plan.planner import plan_physical


class _ReplanRequest(Exception):
    """Internal: restart execution after a strategy re-plan."""


DISPATCH_POLL_KEY = "spark_tpu.execution.dispatchPollMs"


def _sync_dispatched(outs, conf):
    """Host-sync a dispatched stage's stats channel, cancellably.

    `jax.device_get` blocks until the device computation completes, so
    a cancel of a DISPATCHED stage used to land only when the stage
    finished. With a cancel token installed and dispatchPollMs > 0,
    poll the output arrays' readiness instead: each tick checks the
    token, so a DELETE /queries/<id> or a blown queryDeadlineMs raises
    the structured lifecycle error within ~one poll interval (the
    device compute keeps running in the background — XLA offers no
    kill — but the host thread, its leases and its session lease are
    released promptly). Checks the token DIRECTLY rather than through
    lifecycle.checkpoint: readiness polling is timing-dependent, and
    routing it through the `cancel_point` chaos seam would make the
    cancel matrix's nth-boundary targeting nondeterministic.

    The tick ramps 1ms -> dispatchPollMs (doubling): short stages —
    the overwhelmingly common case on a serving path — pay ~1ms of
    added sync latency instead of a full poll interval, while the
    cancel-latency bound for long stages stays ~dispatchPollMs."""
    from . import lifecycle
    tok = lifecycle.current_token()
    poll_ms = float(conf.get(DISPATCH_POLL_KEY) or 0)
    if tok is not None and poll_ms > 0:
        leaves = [a for a in jax.tree_util.tree_leaves(outs)
                  if hasattr(a, "is_ready")]
        tick_s = min(0.001, poll_ms / 1e3)
        while not all(a.is_ready() for a in leaves):
            tok.check("dispatch_wait")
            tok.wait(tick_s)
            tick_s = min(tick_s * 2, poll_ms / 1e3)
    return jax.device_get(outs)


class QueryExecution:
    def __init__(self, session, logical: L.LogicalPlan):
        from ..observability import SpanRecorder
        self.session = session
        self.logical = logical
        self._analyzed: Optional[L.LogicalPlan] = None
        self._optimized: Optional[L.LogicalPlan] = None
        self._executed: Optional[P.PhysicalPlan] = None
        self.phase_times: Dict[str, float] = {}
        self.last_metrics: Dict[str, float] = {}  # ints except rtf_build_ms_*
        # observability: lifecycle identity + per-phase spans (Chrome
        # -trace exportable) + the XLA cost/memory analysis of every
        # stage this execution compiled or reused (observability/)
        self.query_id: int = session._next_query_id()
        self.spans = SpanRecorder(
            self.query_id,
            max_spans=int(session.conf.get(
                "spark_tpu.sql.observability.maxSpans")),
            max_shard_records=int(session.conf.get(
                "spark_tpu.sql.observability.maxShardRecords")))
        self.stage_costs: Dict[str, dict] = {}
        # capacity/size predictions harvested from the planned tree
        # (analysis/predictions.py) — graded against observed metrics
        # by history.prediction_report / grade_predictions
        self.plan_predictions: Optional[list] = None
        # cost-based join-reorder decisions (plan/join_reorder.py);
        # None until the optimizer ran for this execution
        self.reorder_decisions: Optional[list] = None
        # per-(batch, rule) application records from the plan-change
        # tracer (analysis/plan_integrity.py): the event-log rule_trace
        # payload + explain(rules=True); None until the optimizer ran
        self.rule_trace: Optional[list] = None
        # lite-mode plan-integrity findings, merged into
        # analysis_findings by _analyze_plan_phase (full mode raises
        # PlanIntegrityError from inside the optimizer instead)
        self._integrity_findings: list = []
        # set per execute_batch: False keeps event construction off the
        # hot path when nothing is listening
        self._observe_events = False
        self.spilled_partial_rows: Optional[int] = None
        # adaptive strategy re-plans (DynamicJoinSelection.scala:1):
        # {join_tag: strategy}, applied by executed_plan on re-plan
        self._join_overrides: Dict[str, str] = {}
        # failure handling (execution/failures.py): a degraded rerun
        # overlays conf (mesh fallback / spill reroute) without mutating
        # the session; counters feed the event log's fault_summary
        self._exec_conf = None  # Conf overlay, or None = session conf
        self._mesh_fallback = False
        self._oom_rung = 0
        self._retry_policy = None
        # elastic-mesh gang-restart budget (parallel/elastic.py),
        # created per execute_batch like the retry policy
        self._elastic = None
        self._last_stage_key: Optional[str] = None
        self.fault_summary: Dict[str, object] = {}
        self.fault_events: list = []
        # partial-progress recovery (execution/recovery.py): chunk
        # retrier conf + stage-output memo + mesh checkpoints, created
        # per execute_batch / external collect
        self._recovery = None
        # pre-compile static analysis (spark_tpu/analysis/): typed
        # findings from the plan walk + (gated) jaxpr walk; None until
        # the analyzer ran for this execution
        self.analysis_findings: Optional[list] = None
        self._analysis_posted = False
        # python-UDF evaluation summary (execution/python_eval.py):
        # the event-log `udf` record — mode, batch/row totals, worker
        # restarts; None when the query had no UDFs
        self.udf_summary: Optional[Dict] = None

    @property
    def _conf(self):
        """Effective conf for planning/execution: the session conf, or a
        degraded-mode overlay (mesh fallback pins mesh.size=0, the OOM
        ladder's spill rung pins a 1-byte device budget)."""
        return self._exec_conf if self._exec_conf is not None \
            else self.session.conf

    def _activate_conf(self) -> None:
        """Apply session conf to analysis-time context (the reference's
        SQLConf thread-activation — ContextVar-backed so concurrent
        service queries on other threads keep their own value)."""
        from ..expr import set_case_sensitive
        set_case_sensitive(bool(
            self.session.conf.get("spark_tpu.sql.caseSensitive")))

    @property
    def analyzed(self) -> L.LogicalPlan:
        if self._analyzed is None:
            t0 = time.perf_counter()
            self._activate_conf()
            self.logical.schema()  # eager name/type resolution raises here
            self._analyzed = self.logical
            t1 = time.perf_counter()
            self.phase_times["analysis"] = t1 - t0
            self.spans.record("analysis", t0, t1)
        return self._analyzed

    def _apply_cache(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        """Substitute cached subtrees with scans over their materialized
        tables (reference: CacheManager.useCachedData). A MARKED but
        not-yet-materialized subtree appearing in any query materializes
        on first use, like the reference's InMemoryRelation. Matching is
        on the pre-optimization plan fingerprint."""
        session = self.session
        if not session._data_cache and not session._cache_requests:
            return plan
        root_fp = session._plan_fingerprint(plan)

        def f(node):
            fp = session._plan_fingerprint(node)
            table = session._data_cache.get(fp)
            if table is not None:
                # shared (service) or per-session result-cache hit: the
                # subtree replays from the materialized Arrow table
                session.metrics.counter("result_cache_hits").inc()
            if table is None and fp in session._cache_requests \
                    and fp != root_fp:
                # first use inside a larger query: materialize now (the
                # fp != root_fp guard leaves root execution to the
                # normal path, which fills the cache afterwards)
                sub = QueryExecution(session, session._cache_requests[fp])
                table = sub.collect()
                session._data_cache[fp] = table
            if table is not None:
                from ..io.sources import ArrowTableSource
                return L.Scan(ArrowTableSource("__cached__", table))
            return None

        # top-down so the largest cached subtree wins
        return plan.transform_down(f)

    def _resolve_scalar_subqueries(self, plan: L.LogicalPlan
                                   ) -> L.LogicalPlan:
        """Execute uncorrelated scalar subqueries and substitute their
        single value as a Literal — BEFORE optimization so the literal
        participates in pushdown (reference: PlanSubqueries +
        ScalarSubquery execution)."""
        from ..expr import Literal

        def expr_has(e) -> bool:
            if isinstance(e, L.ScalarSubqueryExpr):
                return True
            return any(expr_has(c) for c in e.children)

        if not any(expr_has(e) for e in L.iter_expressions(plan)):
            return plan  # skip the rebuild on the no-subquery hot path

        def fix(e):
            def f(node):
                if isinstance(node, L.ScalarSubqueryExpr):
                    if len(node.plan.schema().fields) != 1:
                        raise RuntimeError(
                            "scalar subquery must return exactly one "
                            "column")
                    table = QueryExecution(self.session,
                                           node.plan).collect()
                    if table.num_rows > 1:
                        raise RuntimeError(
                            "scalar subquery returned more than one row")
                    dt = node.plan.schema().fields[0].dtype
                    val = None if table.num_rows == 0 else \
                        table.column(0)[0].as_py()
                    return Literal(val, dt)
                return node
            return e.transform_up(f)

        return L.map_expressions(plan, fix)

    @property
    def optimized_plan(self) -> L.LogicalPlan:
        if self._optimized is None:
            t0 = time.perf_counter()
            plan = self._apply_cache(self.analyzed)
            plan = self._resolve_scalar_subqueries(plan)
            log: list = []
            from ..analysis.plan_integrity import (PlanChangeTracer,
                                                   PlanIntegrityValidator)
            mode = str(self._conf.get(
                "spark_tpu.sql.planChangeValidation"))
            validator = PlanIntegrityValidator(mode) \
                if mode in ("lite", "full") else None
            tracer = PlanChangeTracer(diffs=bool(self._conf.get(
                "spark_tpu.sql.planChangeLog")))
            self._optimized = default_optimizer(
                self._conf, reorder_log=log, validator=validator,
                tracer=tracer).execute(plan)
            # cost-based join-reorder decisions (plan/join_reorder.py):
            # one record per eligible region, into the event log and
            # the explain()/history API "reorder: yes/no" annotation
            self.reorder_decisions = log
            self.rule_trace = tracer.records
            if validator is not None:
                self._integrity_findings = validator.findings
            t1 = time.perf_counter()
            self.phase_times["optimization"] = t1 - t0
            self.spans.record("optimize", t0, t1)
        return self._optimized

    @property
    def executed_plan(self) -> P.PhysicalPlan:
        if self._executed is None:
            t0 = time.perf_counter()
            self._executed = plan_physical(
                self.optimized_plan, self._conf,
                join_strategy_overrides=self._join_overrides or None)
            t1 = time.perf_counter()
            self.phase_times["planning"] = t1 - t0
            self.spans.record("plan", t0, t1)
        return self._executed

    def explain(self, extended: bool = False, runtime: bool = False,
                analysis: bool = False, rules: bool = False) -> str:
        out = []
        if extended:
            out += ["== Logical Plan ==", self.logical.tree_string(),
                    "== Optimized Logical Plan ==",
                    self.optimized_plan.tree_string()]
        if runtime and self.last_metrics:
            out.append("== Physical Plan (runtime metrics) ==")
            out.append(self._runtime_tree(self.executed_plan))
            if self.stage_costs:
                out.append("== Stage cost (XLA) ==")
                for info in self.stage_costs.values():
                    bits = [f"stage {info.get('key_hash', '?')}"]
                    for k, label in (("flops", "flops"),
                                     ("bytes_accessed", "bytes"),
                                     ("peak_hbm_bytes", "peak HBM")):
                        if info.get(k) is not None:
                            bits.append(f"{label}={info[k]:,}")
                    if info.get("analysis_ms") is not None:
                        bits.append(f"analysis={info['analysis_ms']}ms")
                    out.append("  " + " ".join(bits))
        else:
            out += ["== Physical Plan ==",
                    self.executed_plan.tree_string()]
        out += ["== Join Reorder =="] + self._reorder_lines()
        if rules:
            # per-rule effectiveness trace from the plan-change tracer
            # (optionally with before/after diffs under planChangeLog)
            from ..analysis.plan_integrity import render_trace
            self.optimized_plan  # ensure the optimizer (and tracer) ran
            out.append("== Rule Trace ==")
            out += render_trace(self.rule_trace or []) or \
                ["  no rules applied"]
        if analysis:
            out.append("== Static Analysis ==")
            findings = self.analysis_findings
            if findings is None:
                # not executed yet: run the (pure, host-side) plan walk
                # on demand — the jaxpr half needs loaded inputs and
                # only rides an actual execution
                from ..analysis import analyze_plan
                mesh_n = max(1, int(self._conf.get(
                    "spark_tpu.sql.mesh.size")))
                findings = analyze_plan(self.executed_plan, self._conf,
                                        mesh_n)
            if findings:
                out += ["  " + f.render() for f in findings]
            else:
                out.append("  no findings")
        return "\n".join(out)

    def _reorder_lines(self) -> List[str]:
        """Human-readable cost-based join-reorder annotation for
        explain(): 'reorder: yes/no' plus, per region, the frontend
        order, the chosen order, and the per-join estimated rows."""
        self.executed_plan  # ensure the optimizer (and its log) ran
        decisions = self.reorder_decisions or []
        changed = [d for d in decisions if d.get("changed")]
        lines = [f"  reorder: {'yes' if changed else 'no'}"
                 + (f" ({len(changed)}/{len(decisions)} regions)"
                    if decisions else "")]
        for d in decisions:
            if not d.get("changed"):
                arrow = " (kept)"
            elif d.get("kind") == "orientation":
                arrow = " -> same order, probe/build orientation flipped"
            else:
                arrow = " -> " + " * ".join(d["order"])
            est = d.get("est_rows") or []
            lines.append("  " + " * ".join(d["relations"]) + arrow
                         + (f"  est rows/join: {est}" if est else ""))
        return lines

    def _runtime_tree(self, node: P.PhysicalPlan, depth: int = 0) -> str:
        """Tree annotated with per-operator runtime observables (the
        SQL-UI plan graph analog of `metric/SQLMetrics.scala:40`):
        output rows everywhere, plus join actual-vs-capacity, exchange
        max-bucket-vs-capacity, and runtime-filter pruned/tested."""
        m = self.last_metrics
        notes = []
        rows = m.get(f"rows_{getattr(node, 'op_tag', '')}")
        if rows is not None:
            notes.append(f"rows out: {rows:,}")
        tag = getattr(node, "tag", None)
        if isinstance(node, P.JoinExec):
            jr = m.get(f"join_rows_{tag}")
            if jr is not None:
                cap = node.out_cap
                notes.append(f"join rows: {jr:,}"
                             + (f"/{cap:,} cap" if cap else ""))
            if getattr(node, "cbo_est_rows", None) is not None:
                # the reorder cost model's output estimate, next to the
                # observed rows it is graded against
                notes.append(f"cbo est: {node.cbo_est_rows:,}")
            slots = m.get(f"join_table_slots_{tag}")
            if slots is not None:
                # present only when the hash kernel ran this join
                notes.append(
                    f"hash table: {slots:,} slots, build "
                    f"{m.get(f'join_build_ms_{tag}', 0)}ms, probe "
                    f"{m.get(f'join_probe_ms_{tag}', 0)}ms")
        elif isinstance(node, P.ExchangeExec):
            mx = m.get(f"exch_max_{tag}")
            if mx is not None:
                cap = node.block_cap
                notes.append(f"exch max: {mx:,}"
                             + (f"/{cap:,} cap" if cap else ""))
            er = m.get(f"exch_rows_{tag}")
            if er is not None:
                notes.append(f"exch rows: {er:,}")
        elif isinstance(node, P.RuntimeFilterExec):
            tested = m.get(f"rtf_tested_{tag}")
            pruned = m.get(f"rtf_pruned_{tag}")
            if tested is not None and pruned is not None:
                notes.append(f"rtf pruned: {pruned:,}/{tested:,}")
        note = f"   [{'; '.join(notes)}]" if notes else ""
        line = "  " * depth + node.simple_string() + note
        return "\n".join([line] + [self._runtime_tree(c, depth + 1)
                                   for c in node.children])

    # -- execution ----------------------------------------------------------

    def _collect_scans(self, node: P.PhysicalPlan,
                       out: List[P.LeafExec]) -> None:
        if getattr(node, "needs_input", False):
            out.append(node)
        for c in node.children:
            self._collect_scans(c, out)

    def _splice_stream(self, node: P.PhysicalPlan, tagged):
        """Splice one streamed-aggregate result back into the plan.
        `tagged` is ("direct", Batch) / ("mesh", partial Batch) /
        ("spill", (host partial table, partial node)) — the same tagged
        value the recovery stage-output memo retains, so a recovery
        re-execution rebuilds the splice without re-streaming."""
        kind, result = tagged
        if kind == "direct":
            return P.InputExec(result, node.schema(), label="streamed_agg")
        if kind == "mesh":
            spliced = P.InputExec(result, node.schema(),
                                  label="streamed_partial_agg")
            # the final aggregate above resolves its functions
            # against the PRE-aggregation schema
            spliced._agg_base_schema = node._base_schema()
            return spliced
        # "spill": host-spilled partials re-reduce in a FINAL aggregate
        # (the partial -> exchange -> final split of AggUtils.scala,
        # with host Arrow buffers in the exchange's seat)
        from ..columnar import bucket_capacity
        from ..expr import ColumnRef
        partial_table, partial_node = result
        inp = P.InputExec(Batch.from_arrow(partial_table),
                          partial_node.schema(),
                          label="spilled_partials")
        inp._agg_base_schema = node._base_schema()
        final_groups = [ColumnRef(g.name()) for g in node.group_exprs]
        final = P.HashAggregateExec(
            inp, final_groups, node.agg_exprs, mode="final",
            est_groups=bucket_capacity(max(partial_table.num_rows, 8)))
        final.tag = node.tag
        self.spilled_partial_rows = partial_table.num_rows
        return final

    def _materialize_streaming(self, node: P.PhysicalPlan,
                               mesh=None) -> P.PhysicalPlan:
        """Execute streamable aggregates eagerly (chunked, accumulator
        carry) and splice their results back as InputExec leaves. Under a
        mesh, PARTIAL aggregates over chunked scans stream with per-shard
        tables (the exchange + final stages above run unchanged).

        Completed streams land in the recovery stage-output memo (the
        surviving-shuffle-file analog): when a downstream failure
        re-executes the query, the splice replays from the memo instead
        of re-ingesting the stream. After a mesh failure, a matching
        mesh checkpoint resumes the stream at its chunk cursor."""
        from .streaming_agg import (resume_from_mesh_checkpoint,
                                    stream_scan_aggregate_mesh,
                                    try_stream_aggregate,
                                    try_stream_aggregate_spill)
        rec = self._recovery
        cache = self.session._stage_cache
        if mesh is None and isinstance(node, P.HashAggregateExec):
            memo_key = ("stream", id(node))
            if rec is not None:
                hit = rec.memo_get(memo_key, label=node.simple_string())
                if hit is not None:
                    return self._splice_stream(node, hit)
                if self._mesh_fallback:
                    resumed = resume_from_mesh_checkpoint(
                        node, self._conf, cache, rec)
                    if resumed is not None:
                        rec.memo_put(memo_key, ("spill", resumed))
                        return self._splice_stream(node,
                                                   ("spill", resumed))
            result = try_stream_aggregate(node, self._conf, cache, rec)
            if result is not None:
                if rec is not None:
                    rec.memo_put(memo_key, ("direct", result))
                return self._splice_stream(node, ("direct", result))
            spill = try_stream_aggregate_spill(node, self._conf, cache,
                                               rec)
            if spill is not None:
                if rec is not None:
                    rec.memo_put(memo_key, ("spill", spill))
                return self._splice_stream(node, ("spill", spill))
        if mesh is not None and isinstance(node, P.HashAggregateExec) \
                and node.mode == "partial":
            memo_key = ("stream_mesh", id(node))
            if rec is not None:
                hit = rec.memo_get(memo_key, label=node.simple_string())
                if hit is not None:
                    return self._splice_stream(node, hit)
            result = stream_scan_aggregate_mesh(
                node, mesh, self._conf, cache, rec)
            if result is not None:
                if rec is not None:
                    rec.memo_put(memo_key, ("mesh", result))
                return self._splice_stream(node, ("mesh", result))
        new_children = tuple(self._materialize_streaming(c, mesh)
                             for c in node.children)
        if new_children != node.children:
            import copy
            node = copy.copy(node)
            node.children = new_children
        return node

    def _materialize_generates(self, node: P.PhysicalPlan
                               ) -> P.PhysicalPlan:
        """Mesh runs: offsets-encoded list columns cannot shard (their
        offsets are absolute into the flattened values), so explode
        subtrees materialize single-device and the FLAT exploded result
        shards as an InputExec — the stage cut the reference makes at
        GenerateExec.scala:1, with the generate on the driver device."""
        new_children = tuple(self._materialize_generates(c)
                             for c in node.children)
        if new_children != node.children:
            import copy
            node = copy.copy(node)
            node.children = new_children
        if isinstance(node, P.GenerateExec):
            from .streaming_agg import _materialize_subtree
            b = _materialize_subtree(node, self._conf, self._recovery)
            return P.InputExec(b, node.schema(), label="generated")
        return node

    def _stage_key(self, root: P.PhysicalPlan, mesh=None) -> str:
        from .streaming_agg import conf_compile_suffix
        conf = self._conf
        n = int(mesh.devices.size) if mesh is not None else 1
        metrics_on = bool(conf.get("spark_tpu.sql.metrics.enabled"))
        return (root.describe()
                + (f"#mesh{n}" if mesh is not None else "")
                + f"#m{int(metrics_on)}"
                + conf_compile_suffix(conf))

    def _events_enabled(self) -> bool:
        """Whether lifecycle events are worth constructing at all: an
        observability output is configured, or a non-built-in listener
        is registered. With neither, posting would render plan strings
        and span dicts per query for three subscribers that each check
        conf and do nothing — pure hot-path waste."""
        conf = self.session.conf
        if str(conf.get("spark_tpu.sql.eventLog.dir")) \
                or str(conf.get("spark_tpu.sql.trace.dir")) \
                or str(conf.get("spark_tpu.sql.metrics.sink")):
            return True
        return any(not getattr(li, "_builtin", False)
                   for li in self.session.listeners.listeners)

    def _shard_obs_on(self) -> bool:
        """Gate for per-shard telemetry (mesh runs only): 'on' always,
        'off' never, 'auto' whenever lifecycle events are observed —
        the same discipline as xlaCost, so a service/event-logged mesh
        query gets its flight-recorder records and a bare CLI run pays
        nothing."""
        mode = str(self._conf.get(
            "spark_tpu.sql.observability.shardSpans"))
        if mode == "off":
            return False
        if mode == "on":
            return True
        return self._observe_events

    def _observe_cost(self) -> bool:
        """Gate for XLA cost/memory capture (it costs a second compile
        of the stage): 'on' always, 'off' never, 'auto' only when an
        observability output is configured or the OOM ladder is
        descending (the rung-3 diagnostic cites measured HBM)."""
        conf = self._conf
        mode = str(conf.get("spark_tpu.sql.observability.xlaCost"))
        if mode == "off":
            return False
        if mode == "on":
            return True
        return bool(str(self.session.conf.get("spark_tpu.sql.eventLog.dir"))
                    or str(self.session.conf.get("spark_tpu.sql.trace.dir"))
                    or str(self.session.conf.get(
                        "spark_tpu.sql.metrics.sink"))
                    or self._oom_rung > 0)

    def _capture_stage_cost(self, fn, key: str, args,
                            compiled=None) -> Optional[dict]:
        """cost_analysis()/memory_analysis() per stage key, memoized on
        the session (a stage recompiles only when its key changes, so
        the analysis stays valid). Fault injection is suppressed around
        the analysis lowering: it re-traces the stage, and trace-time
        chaos sites must count once per REAL compile. When a `Compiled`
        is already in hand (the AOT compile-cache path, or a wrapper
        holding one for these args), it is analyzed directly — no
        second analysis compile."""
        import hashlib
        from ..observability import xla_cost
        from ..testing import faults
        from . import compile_cache as CC
        info = self.session._stage_costs.get(key)
        if info is None and args is not None and self._observe_cost():
            if compiled is None and isinstance(fn, CC.CachedStageFn):
                compiled = fn.compiled_for(args)
            t0 = time.perf_counter()
            if compiled is not None:
                info = xla_cost.analyze_compiled(compiled)
            else:
                with faults.suppressed():
                    info = xla_cost.analyze_jit(fn, args)
            info["analysis_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 1)
            info["key_hash"] = hashlib.md5(
                key.encode()).hexdigest()[:10]
            info["stage"] = key[:160]
            if "error" not in info:
                # memoize successes only: a failed analysis (e.g. the
                # analysis compile itself OOMed mid-ladder) must retry
                # next time instead of pinning the error forever
                store = self.session._stage_costs
                store[key] = info
                while len(store) > 512:
                    store.pop(next(iter(store)))
        if info is not None:
            self.stage_costs[key] = info
        return info

    def _build_stage_fn(self, root: P.PhysicalPlan, mesh=None):
        """Construct the stage callable (pre-jit): the replay of the
        operator tree over input batches, shard_map-wrapped under a
        mesh. One builder serves both consumers — `_compile_stage` jits
        exactly this, and the jaxpr analyzer abstractly evaluates
        exactly this — so the analysis can never drift from the
        compiled program."""
        conf = self._conf
        per_op = bool(conf.get("spark_tpu.sql.metrics.enabled"))

        def replay_root(ctx, inputs):
            counter = [0]

            def replay(node: P.PhysicalPlan) -> Batch:
                if getattr(node, "needs_input", False):
                    b = inputs[counter[0]]
                    counter[0] += 1
                    return b
                child_batches = [replay(c) for c in node.children]
                out = node.compute(ctx, child_batches)
                if per_op:
                    # rows-out per operator, psum'd across shards — the
                    # SQLMetrics.scala:40 analog, shown by
                    # explain(runtime=True)
                    ctx.add_metric(
                        f"rows_{getattr(node, 'op_tag', 'op?')}",
                        jnp.sum(out.selection_mask().astype(jnp.int64)))
                return out

            return replay(root)

        if mesh is None:
            def run(inputs):
                ctx = P.ExecContext(conf)
                out = replay_root(ctx, inputs)
                return out, ctx.flags, ctx.metrics

            return run
        else:
            from jax.sharding import PartitionSpec as Psp
            from ..parallel.mesh import shard_map
            from ..parallel import stripe_batch
            from ..parallel.mesh import AXIS

            n = int(mesh.devices.size)

            # sorted/limited/global-agg results are replicated on every
            # shard; each shard emits its contiguous stripe so the
            # out_spec reassembles the full (ordered) result exactly once
            replicated_out = isinstance(
                root.output_partitioning(),
                (P.SinglePartition, P.Replicated))

            def run_shard(inputs, _token):
                ctx = P.ExecContext(conf, axis_name=AXIS, n_shards=n)
                out = replay_root(ctx, inputs)
                if replicated_out:
                    out = stripe_batch(out, ctx)
                # AQE stats channel: reduce flags/metrics to replicated
                # scalars (pmax for per-shard capacity stats, psum else)
                flags = {k: jax.lax.psum(
                    jnp.asarray(v).astype(jnp.int32), AXIS)
                    for k, v in ctx.flags.items()}
                metrics = {}
                for k, v in ctx.metrics.items():
                    # capacity-sizing stats take the worst shard (pmax);
                    # row counts sum across shards
                    red = jax.lax.pmax if k.startswith(
                        ("join_rows_", "exch_max_", "agg_groups_",
                         "rtf_build_ms_", "join_build_ms_",
                         "join_probe_ms_", "join_table_slots_")) \
                        else jax.lax.psum
                    metrics[k] = red(jnp.asarray(v), AXIS)
                return out, flags, metrics

            return shard_map(
                run_shard, mesh=mesh,
                in_specs=(Psp(AXIS), Psp(AXIS)),
                out_specs=(Psp(AXIS), Psp(), Psp()),
                check_vma=False)

    def _compile_stage(self, root: P.PhysicalPlan, mesh=None, args=None):
        from ..observability.listener import StageCompiledEvent
        from ..testing import faults
        from . import compile_cache as CC
        from . import lifecycle
        # cooperative boundary before paying (or re-paying) a compile
        lifecycle.checkpoint("compile")
        key = self._stage_key(root, mesh)
        self._last_stage_key = key  # recovery evicts exactly this entry
        cc = CC.get_cache(self._conf) if args is not None else None
        if cc is not None:
            plan = faults.active()
            if plan is not None and any(
                    r.site in faults.TRACE_TIME_SITES
                    for r in plan.rules):
                # trace-time chaos seams fire once per (re)compile; a
                # deserialized executable involves no trace, so the
                # armed rule's nth hit would silently never arrive
                # (and a transient-retry eviction would stop forcing
                # the re-trace the seam contract documents). Chaos
                # determinism wins: bypass the disk cache while such
                # rules are armed.
                cc = None
        fn = self.session._stage_cache.get(key)
        partial = None
        if fn is not None and isinstance(fn, CC.CachedStageFn):
            if not fn.has_builder:
                # warm-start entries arrive builder-less; bind the jit
                # fallback here (only the executor owns the plan) so a
                # novel call signature can still compile. The thunk
                # closes over the PRE-BUILT stage fn (conf + plan
                # only) — never `self`: these wrappers live in the
                # session-lifetime shared stage cache, and capturing
                # the QueryExecution would pin its recovery memo's
                # materialized batches per cached key
                stage_fn = self._build_stage_fn(root, mesh)
                fn.bind_builder(lambda: jax.jit(stage_fn))
            if cc is not None and fn.compiled_for(args) is None:
                # the KEY is warm but THIS call signature is not
                # (another dictionary encoding / batch shape): the
                # disk may already hold its executable from another
                # process or an earlier run — fall through to fill
                # the existing wrapper, so the "never jit a known
                # shape twice" contract holds per SIGNATURE, not
                # merely per key (and a fresh compile here gets
                # persisted instead of hiding in the jit fallback)
                partial = fn
                fn = None
        if fn is not None:
            self.session.metrics.counter("compile_cache_hits").inc()
            self._capture_stage_cost(fn, key, args)
            self._last_compile_was_miss = False
            return fn
        self.session.metrics.counter("compile_cache_misses").inc()
        self._last_compile_was_miss = True
        t_compile = time.perf_counter()
        faults.fire("stage_compile")  # chaos seam: pre-jit, cache miss
        if mesh is not None:
            faults.fire("mesh")  # chaos seam: mesh/shard_map lowering
        compiled = None
        disk_hit = False
        if cc is not None:
            # persistent cross-process seat: deserialize instead of
            # compiling when a matching executable is on disk
            t_deser = time.perf_counter()
            compiled = cc.load(key, mesh, args,
                               metrics=self.session.metrics)
            if compiled is not None:
                disk_hit = True
                self.spans.record("deserialize", t_deser,
                                  time.perf_counter())
        if cc is not None:
            # either cc branch pays compile/deserialize EAGERLY here,
            # so the first dispatch carries no jit compile — the
            # dispatch span's includes_jit_compile flag must not
            # attribute cost this span already carries
            self._last_compile_was_miss = False
        if compiled is not None:
            if partial is not None:
                fn = partial
            else:
                # builder closes over the pre-built stage fn only (see
                # the warm-start bind above for why `self` must not
                # leak in)
                stage_fn = self._build_stage_fn(root, mesh)
                fn = CC.CachedStageFn(lambda: jax.jit(stage_fn))
            fn.add(CC.call_signature(args), compiled)
        elif cc is not None:
            # AOT path: pay trace + backend compile NOW (the lazy jit
            # would pay the same at first dispatch) so the executable
            # can be serialized for the next process
            jitted = jax.jit(self._build_stage_fn(root, mesh))
            compiled = jitted.lower(*args).compile()
            cc.store(key, mesh, args, compiled,
                     metrics=self.session.metrics)
            fn = partial if partial is not None \
                else CC.CachedStageFn(lambda: jitted)
            fn.add(CC.call_signature(args), compiled)
        else:
            fn = jax.jit(self._build_stage_fn(root, mesh))
        self.session._stage_cache[key] = fn
        cost = self._capture_stage_cost(fn, key, args, compiled=compiled)
        t1 = time.perf_counter()
        # honesty note: jax.jit is lazy — the EXECUTING program's XLA
        # compile happens inside the first dispatch (that dispatch span
        # carries includes_jit_compile=True). Under the compile cache
        # the AOT path is EAGER, so this span carries the true compile
        # (or deserialize) cost. Without it, the span covers stage
        # setup plus, when capture is on, the AOT analysis compile
        # (whose wall-clock rides in the analysis_ms attr).
        attrs = {"stage": (cost or {}).get("key_hash", key[:60])}
        if cc is not None:
            attrs["disk_hit"] = disk_hit
        if cost and cost.get("analysis_ms") is not None:
            attrs["analysis_ms"] = cost["analysis_ms"]
        self.spans.record("compile", t_compile, t1, **attrs)
        if self._observe_events:
            self.session.listeners.post(
                "on_stage_compiled", StageCompiledEvent(
                    query_id=self.query_id, ts=time.time(), stage_key=key,
                    key_hash=(cost or {}).get("key_hash", ""),
                    mesh_n=int(mesh.devices.size) if mesh is not None else 1,
                    cost=cost))
        return fn

    # -- pre-compile static analysis (spark_tpu/analysis/) ------------------

    def _analysis_conf(self):
        conf = self._conf
        return (bool(conf.get("spark_tpu.sql.analysis.enabled")),
                bool(conf.get("spark_tpu.sql.analysis.strict")))

    def _jaxpr_analysis_on(self, strict: bool) -> bool:
        """Gate for the jaxpr half (one extra abstract trace per unique
        stage key, memoized): mirrors the xlaCost 'auto' discipline."""
        mode = str(self._conf.get("spark_tpu.sql.analysis.jaxpr"))
        if mode == "off":
            return False
        if mode == "on":
            return True
        return strict or self._events_enabled()

    def _post_analysis(self, strict: bool) -> None:
        """Publish findings on the bus (once per execution) and raise
        pre-compile under strict when any is error-severity."""
        from ..analysis import AnalysisFindingError, errors_of
        from ..observability.listener import AnalysisEvent
        findings = self.analysis_findings or []
        if findings and self._observe_events and not self._analysis_posted:
            self._analysis_posted = True
            self.session.listeners.post("on_analysis", AnalysisEvent(
                query_id=self.query_id, ts=time.time(),
                findings=[f.to_dict() for f in findings]))
        if strict and errors_of(findings):
            raise AnalysisFindingError(findings)

    def _analyze_plan_phase(self) -> None:
        """Plan-level walk of the planned tree — BEFORE streaming
        splices/UDF extraction execute anything, so strict mode rejects
        a hazardous plan with zero device work done."""
        enabled, strict = self._analysis_conf()
        if not enabled:
            # leave None ("never analyzed"), NOT [] ("analyzed clean"):
            # explain(analysis=True) runs its on-demand walk off the
            # None sentinel, so a disabled execution can't print a
            # false clean bill. Lite-mode plan-integrity findings still
            # surface — validation ran regardless of the analyzer gate.
            self.executed_plan  # ensure the optimizer (validator) ran
            self.analysis_findings = \
                list(self._integrity_findings) or None
            if self.analysis_findings:
                self._post_analysis(strict=False)
            return
        from ..analysis import analyze_plan
        t0 = time.perf_counter()
        mesh_n = max(1, int(self._conf.get("spark_tpu.sql.mesh.size")))
        # lite-mode plan-integrity findings (collected while the
        # optimizer ran, triggered via executed_plan below) join the
        # analyzer's findings in the same flow
        self.analysis_findings = analyze_plan(self.executed_plan,
                                              self._conf, mesh_n) \
            + list(self._integrity_findings)
        self.spans.record("analyze", t0, time.perf_counter(),
                          findings=len(self.analysis_findings))
        if strict:
            self._post_analysis(strict)

    def _analyze_jaxpr_phase(self, root: P.PhysicalPlan, mesh,
                             args) -> None:
        """Jaxpr-level walk of the exact callable about to be jitted,
        memoized per stage key next to the XLA cost analyses. Appends to
        the plan-phase findings, then publishes the combined set."""
        enabled, strict = self._analysis_conf()
        if not enabled:
            return
        if self._jaxpr_analysis_on(strict):
            from ..analysis import analyze_jaxpr, trace_stage
            from ..testing import faults
            key = "jaxpr#" + self._stage_key(root, mesh)
            memo = self.session._analysis_memo
            found = memo.get(key)
            if found is None:
                t0 = time.perf_counter()
                try:
                    # suppressed(): abstract evaluation re-traces the
                    # stage; trace-time chaos sites must count once per
                    # REAL compile only
                    with faults.suppressed():
                        jaxpr = trace_stage(
                            self._build_stage_fn(root, mesh), args)
                    n = int(mesh.devices.size) if mesh is not None else 1
                    found = analyze_jaxpr(jaxpr, mesh_n=n)
                except Exception as e:  # noqa: BLE001 — advisory only
                    import warnings
                    warnings.warn(f"jaxpr analysis failed (skipped): "
                                  f"{type(e).__name__}: {e}")
                    found = []
                else:
                    memo[key] = found
                    while len(memo) > 512:
                        memo.pop(next(iter(memo)))
                self.spans.record("analyze_jaxpr", t0,
                                  time.perf_counter(),
                                  findings=len(found))
            if found:
                known = {(f.code, f.op) for f in
                         (self.analysis_findings or [])}
                self.analysis_findings = (self.analysis_findings or []) \
                    + [f for f in found if (f.code, f.op) not in known]
        self._post_analysis(strict)

    def _aqe_cache_key(self, mesh) -> Optional[str]:
        """Plan + data-identity key for persisted AQE capacities; None
        (uncacheable) when any scan's source has no identity stamp."""
        tokens = [s.source.cache_token()
                  for s in L.iter_scans(self.optimized_plan)]
        if any(t is None for t in tokens):
            return None
        n = int(mesh.devices.size) if mesh is not None else 1
        return (self.optimized_plan.tree_string()
                + f"#mesh{n}#src{tokens!r}")

    @staticmethod
    def _collect_caps(root: P.PhysicalPlan, out: Dict[str, int]) -> None:
        """Harvest every AQE-discovered static capacity from a converged
        plan, keyed `kind:tag` (the persistence side of the stats
        channel: the reference re-learns MapOutputStatistics per query,
        but its shuffle files are sized dynamically — XLA's static
        shapes make remembering converged capacities the difference
        between one compile and a compile per retry per execution)."""
        for c in root.children:
            QueryExecution._collect_caps(c, out)
        if isinstance(root, P.JoinExec):
            if root.out_cap is not None:
                out[f"join:{root.tag}"] = root.out_cap
            if root.unique_build is False:
                out[f"uniq:{root.tag}"] = 0
            if root.hash_fallback is False:
                out[f"hashfb:{root.tag}"] = 0
        elif isinstance(root, P.ExchangeExec) and root.block_cap is not None:
            out[f"exch:{root.tag}"] = root.block_cap
        elif isinstance(root, P.HashAggregateExec) and root.est_groups:
            out[f"agg:{root.tag}"] = root.est_groups

    def _apply_saved_caps(self, root: P.PhysicalPlan, caps: Dict[str, int]
                          ) -> None:
        for key, cap in caps.items():
            kind, tag = key.split(":", 1)
            if kind == "join":
                self._set_join_cap(root, tag, cap)
            elif kind == "uniq":
                self._set_join_nonunique(root, tag)
            elif kind == "hashfb":
                self._set_join_hash_fallback(root, tag)
            elif kind == "exch":
                self._set_exchange_cap(root, tag, cap)
            else:
                self._set_agg_groups(root, tag, cap)

    @staticmethod
    def _set_join_cap(root: P.PhysicalPlan, tag: str, cap: int) -> None:
        for c in root.children:
            QueryExecution._set_join_cap(c, tag, cap)
        if isinstance(root, P.JoinExec) and root.tag == tag:
            root.out_cap = cap

    @staticmethod
    def _set_join_nonunique(root: P.PhysicalPlan, tag: str) -> None:
        for c in root.children:
            QueryExecution._set_join_nonunique(c, tag)
        if isinstance(root, P.JoinExec) and root.tag == tag:
            root.unique_build = False

    @staticmethod
    def _set_join_hash_fallback(root: P.PhysicalPlan, tag: str) -> None:
        """The hash kernel's open table saturated for this join (a
        collision cluster outran join.hashMaxProbe): pin it to the sort
        kernel and re-jit — a correctness re-plan like the unique-build
        fallback, never capacity growth."""
        for c in root.children:
            QueryExecution._set_join_hash_fallback(c, tag)
        if isinstance(root, P.JoinExec) and root.tag == tag:
            root.hash_fallback = False

    @staticmethod
    def _set_exchange_cap(root: P.PhysicalPlan, tag: str, cap: int) -> None:
        for c in root.children:
            QueryExecution._set_exchange_cap(c, tag, cap)
        if isinstance(root, P.ExchangeExec) and root.tag == tag:
            root.block_cap = cap

    @staticmethod
    def _set_agg_groups(root: P.PhysicalPlan, tag: str, est: int) -> None:
        for c in root.children:
            QueryExecution._set_agg_groups(c, tag, est)
        if isinstance(root, P.HashAggregateExec) and root.tag == tag:
            root.est_groups = est

    def execute_batch(self) -> Tuple[Batch, Dict, Dict]:
        """Run the query, returning (device Batch, flags, metrics).

        Joins whose many-to-many expansion overflows the seeded output
        capacity surface a `join_overflow_<tag>` flag plus the true row
        total in `join_rows_<tag>`; the loop below re-jits those joins
        with a sufficient static capacity (the AQE-style stats->re-plan
        host loop, `AdaptiveSparkPlanExec.scala:64`). A skewed shuffle
        join raises _ReplanRequest instead: the physical plan rebuilds
        with the join forced to broadcast and execution restarts.

        Failures flow through the structured taxonomy
        (execution/failures.py): transient flakes and stage timeouts
        retry with backoff, RESOURCE_EXHAUSTED descends the degradation
        ladder, mesh failures re-plan single-device — all recorded in
        `fault_summary` and the event log."""
        from ..observability.listener import QueryStartEvent
        from ..parallel.elastic import ElasticMeshState
        from ..service import arbiter as res_arbiter
        from ..testing import faults
        from .failures import RetryPolicy
        from .recovery import RecoveryContext
        from . import lifecycle
        self._activate_conf()
        # degraded-mode state was sticky across executions of one
        # QueryExecution: a warm-loop re-execution after a transient
        # mesh failure stayed pinned single-device (and an OOM reroute
        # stayed spill-routed) forever. Every execution starts
        # optimistic — the ladder re-derives whatever it still needs.
        # A plan built under the old overlay must be rebuilt.
        if self._exec_conf is not None or self._mesh_fallback:
            self._executed = None
        self._exec_conf = None
        self._mesh_fallback = False
        faults.arm(self.session.conf)
        # query lifecycle scope (execution/lifecycle.py): install a
        # cancel token (deadline armed from queryDeadlineMs) unless an
        # outer scope — the SQL service, or an enclosing execution
        # whose subquery this is — already did, and register it for
        # session.cancel(query_id)
        lc_scope = lifecycle.enter_query_scope(
            self.session.app_id, self.query_id, self.session.conf)
        # cross-query arbiter lease scope (service/arbiter.py): scans
        # this execution keeps resident lease from the shared HBM pool;
        # everything leased is released when the execution ends. None
        # (free) when no arbiter is installed.
        arb_token = res_arbiter.enter_query(
            f"{self.session.app_id}:q{self.query_id}")
        conf = self._conf
        self.fault_summary = {}
        self.fault_events = []
        self.udf_summary = None
        self._recovery = RecoveryContext(metrics=self.session.metrics,
                                         record=self._record_fault)
        # NOTE: _analysis_posted is NOT reset here — it is
        # per-QueryExecution, so an external-collect attempt that falls
        # through to execute_batch (or a re-executed qe) posts the
        # on_analysis event exactly once
        self.analysis_findings = None
        self._oom_rung = 0
        self._retry_policy = RetryPolicy(
            max_retries=self._max_retries(conf),
            backoff_ms=float(conf.get("spark_tpu.execution.backoffMs")))
        self._elastic = ElasticMeshState(conf)
        self._observe_events = self._events_enabled()
        if self._observe_events:
            self.session.listeners.post("on_query_start", QueryStartEvent(
                query_id=self.query_id, ts=time.time(),
                plan=self.logical.tree_string()))
        self.session._exec_depth += 1
        try:
            for _replan in range(4):
                try:
                    return self._execute_recover()
                except _ReplanRequest:
                    self._executed = None  # re-plan with _join_overrides
                    # the rebuilt plan has fresh node identities and
                    # different shapes: memoized stage outputs no
                    # longer splice (epoch bump)
                    self._recovery.invalidate()
                    self.spans.mark("aqe_replan", kind="join_strategy")
            # replan budget exhausted: finish with capacity growth only
            self._no_more_replans = True
            return self._execute_recover()
        except _ReplanRequest:
            raise
        except (lifecycle.QueryCancelledError,
                lifecycle.QueryDeadlineError) as e:
            self._observe_cancel(e)
            raise
        except Exception as e:  # noqa: BLE001 — observe, then surface
            self._post_query_end(None, status="error", error=e)
            self._flightrec_dump(e)
            raise
        finally:
            res_arbiter.exit_query(arb_token)
            lifecycle.exit_query_scope(lc_scope)
            self.session._exec_depth -= 1
            if self._recovery is not None:
                # the memo spans recovery loops, not executions: drop
                # retained device batches / checkpoint tables now
                self._recovery.release()
            if self.session._exec_depth == 0:
                # implicit (WITH-clause) materializations are statement
                # -scoped: evict when the outermost execution finishes
                self.session._evict_implicit_caches()

    @staticmethod
    def _max_retries(conf) -> int:
        """spark_tpu.execution.maxRetries, unless the deprecated
        spark_tpu.sql.execution.maxTaskFailures was explicitly set (its
        registry default must not shadow the new key)."""
        legacy = "spark_tpu.sql.execution.maxTaskFailures"
        if conf.is_explicitly_set(legacy):
            return int(conf.get(legacy))
        return int(conf.get("spark_tpu.execution.maxRetries"))

    # -- failure recovery ---------------------------------------------------

    def _record_fault(self, action: str, exc=None, **extra) -> None:
        """Count one recovery action into fault_summary, append a
        bounded event record (both land in the event log), post the
        typed FaultEvent, and mark the retry on the span trace."""
        from ..observability.listener import FaultEvent
        self.fault_summary[action] = int(self.fault_summary.get(action, 0)) + 1
        error = "" if exc is None else f"{type(exc).__name__}: {exc}"[:200]
        site = getattr(exc, "site", None)
        if len(self.fault_events) < 32:
            ev = {"action": action}
            if exc is not None:
                ev["error"] = error
                if site is not None:
                    ev["site"] = site
            ev.update(extra)
            self.fault_events.append(ev)
        else:
            # the 32-entry cap used to drop later events SILENTLY —
            # count the truncation so history/event-log consumers can
            # see the record list is incomplete (the action counters
            # above still count everything)
            self.fault_summary["events_dropped"] = int(
                self.fault_summary.get("events_dropped", 0)) + 1
        self.spans.mark(f"retry:{action}", error=error[:120])
        if self._observe_events:
            self.session.listeners.post("on_fault", FaultEvent(
                query_id=self.query_id, ts=time.time(), action=action,
                error=error, site=site))

    def _observe_cancel(self, e: Exception) -> None:
        """Observability for a cancelled/deadlined execution: the
        lifecycle counter, a `cancel` action in fault_summary (history
        FAULT_ACTIONS), a `cancelled` instant span in the Chrome
        trace, and a query-end event whose status ("cancelled" /
        "deadline_exceeded") flows into the event log and the
        service's query-history store."""
        from .lifecycle import QueryCancelledError
        cancelled = isinstance(e, QueryCancelledError)
        status = "cancelled" if cancelled else "deadline_exceeded"
        self.session.metrics.counter(
            "query_cancelled" if cancelled
            else "query_deadline_exceeded").inc()
        self._record_fault("cancel", e)
        self.spans.mark("cancelled",
                        reason="cancel" if cancelled else "deadline")
        # the no-orphan contract holds wherever the cancel lands: even
        # when it hits outside the UDF lane (scan, exchange, a chunked
        # aggregate), no pooled UDF worker survives the query — idle
        # workers respawn on demand, so this only costs a warm start
        pool = getattr(self.session, "_udf_pool", None)
        if pool is not None:
            pool.shutdown()
        self._post_query_end(None, status=status, error=e)

    def _flightrec_dump(self, e: Exception) -> None:
        """Crash-time diagnostics for a SURFACED failure (the recovery
        ladder gave up): classify the terminal error and ask the
        session's flight recorder for a bundle. Cancels/deadlines take
        the `_observe_cancel` path and deliberately never dump —
        stopping a query is lifecycle, not a crash. Never raises, and
        works with events off: the recorder's rings may be sparse then,
        but plan + fault summary ride along in `extra`."""
        try:
            from ..observability.flight_recorder import FlightRecorder
            rec = FlightRecorder.of(self.session)
            if rec is None:
                return
            from .failures import StageOOMError
            if isinstance(e, StageOOMError):
                reason = "oom"
            elif ("recovery did not converge" in str(e)
                  and isinstance(e, RuntimeError)):
                reason = "recovery_nonconvergent"
            else:
                reason = "fatal"
            rec.dump(reason, extra={
                "query_id": self.query_id,
                "plan": self.logical.tree_string()[:2000],
                "fault_summary": {
                    k: v for k, v in self.fault_summary.items()
                    if k != "events"},
            }, error=e)
        except Exception as dump_err:  # noqa: BLE001 — diagnostics only
            import warnings
            warnings.warn(f"flight-recorder trigger failed: {dump_err}")

    def _mesh_replan(self, mesh_size: Optional[int] = None) -> None:
        """Shared reset for the elastic-ladder rungs that change the
        gang's shape (drain, shrink-on-restart, single-device
        fallback): memoized stage outputs can no longer splice
        (checkpoints survive — the next stream resumes from them), and
        the plan rebuilds — under a mesh.size overlay when given, else
        against the conf whose device exclusions just changed."""
        if self._recovery is not None:
            self._recovery.invalidate()
        if mesh_size is not None:
            overlay = Conf(parent=self._conf)
            overlay.set("spark_tpu.sql.mesh.size", mesh_size)
            self._exec_conf = overlay
        self._executed = None

    def _execute_recover(self) -> Tuple[Batch, Dict, Dict]:
        """Run `_execute_batch_inner` under the failure taxonomy: each
        iteration either returns, re-raises (_ReplanRequest, FATAL,
        exhausted budgets), or applies one recovery action and loops."""
        from . import lifecycle
        last: Optional[Exception] = None
        for _ in range(32):  # every action below consumes a bounded budget
            # cooperative boundary at every stage-attempt entry: a
            # cancel/deadline delivered mid-recovery stops the ladder
            # here instead of burning another recovery action
            lifecycle.checkpoint("stage_attempt")
            try:
                return self._execute_batch_inner()
            except _ReplanRequest:
                raise
            except Exception as e:  # noqa: BLE001
                last = e
                self._handle_failure(e)  # raises when unrecoverable
        raise RuntimeError(
            f"stage failure recovery did not converge after 32 recovery "
            f"actions; fault_summary={self.fault_summary}; last error: "
            + ("<none>" if last is None
               else f"{type(last).__name__}: {str(last)[:300]}"))

    def _handle_failure(self, e: Exception) -> None:
        """One step of the recovery ladder. Returns after applying a
        recovery action (caller re-executes); raises when the failure is
        fatal or every applicable budget is exhausted."""
        import warnings
        from .failures import (FailureClass, StageOOMError,
                               StageTimeoutError, classify, is_mesh_failure)
        conf = self._conf
        cls = classify(e)
        msg = f"{type(e).__name__}: {e}"

        # lifecycle control outranks every recovery rung: a cancelled
        # or deadlined query surfaces unchanged — no retry, no
        # degraded re-plan, no gang restart (execution/lifecycle.py)
        if cls is FailureClass.CANCELLED:
            raise

        # graceful decommission (parallel/elastic.py): a drain request
        # surfaced at a chunk boundary — a planned transition, not a
        # failure. Exclude the draining devices at SESSION level (the
        # decommission outlives this query), clear the one-shot
        # request, and re-execute on the reduced gang, which resumes
        # from the checkpoint the drain just forced.
        from ..parallel import elastic as EL
        mesh_on = int(conf.get("spark_tpu.sql.mesh.size")) > 1
        if mesh_on and isinstance(e, EL.MeshDecommissionRequest):
            warnings.warn(
                f"decommissioning mesh shard(s) {sorted(e.shards)} "
                f"(device ids {sorted(e.device_ids)}): draining at the "
                f"chunk boundary and continuing on the reduced gang")
            self._record_fault("decommission", None,
                               shards=sorted(e.shards),
                               devices=sorted(e.device_ids))
            EL.apply_decommission(self.session.conf, e.device_ids)
            if self._recovery is not None:
                self._recovery.begin_recovery_attempt()
            self._mesh_replan()  # the gang shrank: [n, ...] shapes differ
            return

        # mesh/collective failure ladder: gang restart first — the
        # mesh streaming driver resumes at its last checkpoint ON the
        # mesh — and only past the restart budget the single-device
        # fallback (degraded but correct), the final rung. Each rung
        # is gated by its OWN conf: meshFallback.enabled=false still
        # restarts (mesh-or-fail), it just removes the degrade rung.
        if mesh_on and not self._mesh_fallback and is_mesh_failure(e):
            # a pool of <= 1 survivors cannot host a gang: skip the
            # restart rung (a re-mesh would be single-device anyway —
            # that is exactly what the fallback rung below does)
            healthy = EL.healthy_device_count(conf)
            restartable = healthy is None or healthy > 1
            slept = self._elastic.try_restart(self._record_fault) \
                if restartable and self._elastic is not None else None
            if slept is not None:
                warnings.warn(
                    f"mesh stage failure, gang-restarting the mesh "
                    f"(attempt {self._elastic.restarts}/"
                    f"{self._elastic.max_restarts}, backoff "
                    f"{slept:.0f}ms): {msg[:160]}")
                self._record_fault("mesh_restart", e,
                                   attempt=self._elastic.restarts,
                                   backoff_ms=round(slept, 1))
                self.session.metrics.counter("mesh_restart_attempts").inc()
                if self._recovery is not None:
                    self._recovery.begin_recovery_attempt()
                # re-probe the healthy pool: a genuinely lost host
                # shrinks the gang instead of failing the re-mesh —
                # smaller n changes shapes, so memoized outputs drop
                n_conf = int(conf.get("spark_tpu.sql.mesh.size"))
                if healthy is not None and 1 < healthy < n_conf:
                    self._mesh_replan(mesh_size=healthy)
                return
            if bool(conf.get(
                    "spark_tpu.execution.meshFallback.enabled")):
                warnings.warn(
                    f"mesh stage failure, re-planning single-device "
                    f"(mesh_fallback): {msg[:160]}")
                self._record_fault("mesh_fallback", e)
                self._mesh_fallback = True
                if self._recovery is not None:
                    self._recovery.begin_recovery_attempt()
                self._mesh_replan(mesh_size=0)  # no exchanges/sharding
                return
            # no degrade rung (meshFallback.enabled=false): the
            # classification rungs below decide, like pre-elastic

        if cls in (FailureClass.TRANSIENT, FailureClass.TIMEOUT):
            slept = self._retry_policy.attempt_retry()
            if slept is None:
                if cls is FailureClass.TIMEOUT:
                    raise StageTimeoutError(
                        f"stage still over stageTimeoutMs after "
                        f"{self._retry_policy.attempts} retries: "
                        f"{msg[:200]}") from e
                raise  # transient budget exhausted: surface the original
            action = "stage_timeout" if cls is FailureClass.TIMEOUT \
                else "transient_retry"
            # "transient stage failure" prefix is load-bearing: the
            # pre-taxonomy retry loop warned with it and tests match it
            kind = "stage timeout" if cls is FailureClass.TIMEOUT \
                else "transient stage failure"
            warnings.warn(
                f"{kind}, retrying "
                f"({self._retry_policy.remaining} left, "
                f"backoff {slept:.0f}ms): {msg[:160]}")
            self._record_fault(action, e, backoff_ms=round(slept, 1))
            if self._recovery is not None:
                # shapes unchanged: completed upstream stage outputs
                # replay from the memo on the re-execution
                self._recovery.begin_recovery_attempt()
            # drop only THIS stage's compiled entry so the retry
            # recompiles (and trace-time injection sites re-fire
            # deterministically) — except on TIMEOUT: the program was
            # fine, just slow; recompiling the identical stage would
            # re-pay compile inside the next deadline window
            if cls is FailureClass.TRANSIENT \
                    and self._last_stage_key is not None:
                self.session._stage_cache.pop(self._last_stage_key, None)
            return

        if cls is FailureClass.OOM:
            self._oom_rung += 1
            # release this query's arbiter leases before any degraded
            # retry: a genuine RESOURCE_EXHAUSTED means the estimate
            # that backed them was wrong, and the retry's admit
            # decisions must start from a clean slate (the shared pool
            # must not stay pinned by a query that just OOMed)
            from ..service.arbiter import release_current
            release_current()
            if self._oom_rung == 1:
                # rung 1: evict the device-resident table cache (the
                # storage pool) and retry — the UnifiedMemoryManager
                # storage-eviction move
                from ..io.device_cache import CACHE
                # release_current() above dropped THIS query's pins;
                # any still-pinned entries are other running queries'
                # working sets — evicting those frees no HBM (their
                # references stay live) while zeroing the storage
                # accounting they're counted under
                freed = CACHE.evict_bytes(CACHE.nbytes)
                if self._last_stage_key is not None:
                    self.session._stage_cache.pop(self._last_stage_key, None)
                import gc
                gc.collect()
                warnings.warn(f"RESOURCE_EXHAUSTED: evicted device cache "
                              f"({freed} bytes) and retrying: {msg[:160]}")
                self._record_fault("oom_cache_evict", e, freed_bytes=freed)
                if self._recovery is not None:
                    # the memo pins device-resident stage outputs
                    # (build sides, streamed splices): under memory
                    # pressure they are part of the storage pool this
                    # rung exists to evict — drop them so the retry
                    # runs unpinned (reuse is lost, memory is freed)
                    self._recovery.invalidate()
                    self._recovery.begin_recovery_attempt()
                return
            if self._oom_rung == 2 and bool(conf.get(
                    "spark_tpu.execution.oom.spillOnExhausted")):
                # rung 2: re-plan under a 1-byte device budget so the
                # host-spill chunked paths (streaming partial spill /
                # external collect) take over — host RAM as spill tier
                warnings.warn(f"RESOURCE_EXHAUSTED persists: re-routing "
                              f"through the host-spill chunked path: "
                              f"{msg[:160]}")
                self._record_fault("oom_spill_reroute", e)
                if self._recovery is not None:
                    # the deviceBudget re-plan changes streaming shapes
                    self._recovery.invalidate()
                    self._recovery.begin_recovery_attempt()
                overlay = Conf(parent=conf)
                overlay.set("spark_tpu.sql.memory.deviceBudget", 1)
                chunk = int(conf.get(
                    "spark_tpu.sql.execution.streamingChunkRows"))
                overlay.set("spark_tpu.sql.execution.streamingChunkRows",
                            min(chunk, 1 << 22))
                self._exec_conf = overlay
                self._executed = None
                return
            # rung 3: out of moves — diagnostic naming the stage and its
            # capacity stats (issue acceptance: fail with a diagnostic)
            raise StageOOMError(self._oom_diagnostic(e)) from e

        raise  # FATAL: surface unchanged

    def _oom_diagnostic(self, e: Exception) -> str:
        caps: Dict[str, int] = {}
        try:
            if self._executed is not None:
                self._collect_caps(self._executed, caps)
        except Exception:  # noqa: BLE001 — best-effort diagnostics only
            pass
        from ..io.device_cache import CACHE
        from ..observability import xla_cost
        conf = self._conf
        stage = (self._last_stage_key or "<uncompiled>")[:400]
        # measured HBM demand (memory_analysis of the failing stage) vs
        # device capacity — the blind spot this layer exists to close:
        # the ladder's rung order can now be tuned against numbers
        hbm = "n/a (enable spark_tpu.sql.observability.xlaCost)"
        cost = self.session._stage_costs.get(self._last_stage_key or "") \
            or self.stage_costs.get(self._last_stage_key or "")
        if cost and cost.get("peak_hbm_bytes") is None:
            err = cost.get("error") or cost.get("memory_error")
            if err:
                hbm = f"capture failed: {err}"
        if cost and cost.get("peak_hbm_bytes") is not None:
            cap = xla_cost.device_hbm_capacity()
            hbm = (f"measured peak HBM demand "
                   f"{cost['peak_hbm_bytes']:,} bytes "
                   f"(args={cost.get('argument_bytes', 0):,}, "
                   f"temps={cost.get('temp_bytes', 0):,}, "
                   f"out={cost.get('output_bytes', 0):,}) vs "
                   f"device capacity "
                   + (f"{cap:,} bytes" if cap else "unknown"))
        return (
            f"RESOURCE_EXHAUSTED survived the degradation ladder "
            f"(device-cache evict -> host-spill reroute): "
            f"{type(e).__name__}: {str(e)[:200]}\n"
            f"  stage: {stage}\n"
            f"  hbm: {hbm}\n"
            f"  capacity stats (kind:tag -> rows): {caps or 'n/a'}\n"
            f"  deviceCacheBytes={CACHE.nbytes}, "
            f"deviceBudget={conf.get('spark_tpu.sql.memory.deviceBudget')}, "
            f"streamingChunkRows="
            f"{conf.get('spark_tpu.sql.execution.streamingChunkRows')}, "
            f"mesh.size={conf.get('spark_tpu.sql.mesh.size')}")

    def _execute_batch_inner(self) -> Tuple[Batch, Dict, Dict]:
        from ..columnar import bucket_capacity
        from ..parallel.mesh import get_mesh
        from ..testing import faults
        from .failures import StageTimeoutError
        mesh = get_mesh(self._conf)
        if mesh is not None:
            # a drain request no gang this size can ever apply must
            # not stay armed for a future larger mesh
            from ..parallel.elastic import discard_stale_decommission
            discard_stale_decommission(self.session.conf, mesh)
        # seed capacities a previous execution of this plan discovered,
        # so repeated queries skip the overflow->re-jit ramp entirely.
        # The key includes every scan's source identity stamp: caps
        # learned on old data must not seed (possibly too small) after a
        # table is re-registered or a file rewritten.
        aqe_key = self._aqe_cache_key(mesh)
        saved_caps = self.session._aqe_caps.get(aqe_key) \
            if aqe_key is not None else None
        if saved_caps:
            self._apply_saved_caps(self.executed_plan, saved_caps)
        # static analysis, plan half: after planning (with persisted AQE
        # caps applied — they are part of the stage key the recompile
        # check audits), before any streaming splice or compile. Strict
        # mode raises here, pre-compile.
        self._analyze_plan_phase()
        # size/capacity predictions off the planned tree (pure host
        # walk, microseconds): graded post-run against observed metrics
        # — the analyzer-self-grading loop (history.prediction_report)
        try:
            from ..analysis.predictions import predict_plan
            self.plan_predictions = predict_plan(
                self.executed_plan, self._conf,
                int(mesh.devices.size) if mesh is not None else 1)
        except Exception as e:  # noqa: BLE001 — predictions are advisory
            import warnings
            warnings.warn(f"plan prediction walk failed (skipped): "
                          f"{type(e).__name__}: {e}")
            self.plan_predictions = None
        root0 = self.executed_plan
        from .python_eval import extract_python_udfs, plan_has_udfs
        if plan_has_udfs(root0):
            t0 = time.perf_counter()
            root0 = extract_python_udfs(root0, self.session.conf,
                                        qe=self)
            self.phase_times["python_udfs"] = time.perf_counter() - t0
        if mesh is not None:
            root0 = self._materialize_generates(root0)
        t0 = time.perf_counter()
        # per-shard flight recorder (observability/spans.py): the mesh
        # chunk drivers pick the telemetry up from the context var so
        # their signatures stay stable; records land on self.spans
        from ..observability.spans import (ShardStreamTelemetry,
                                           use_shard_telemetry)
        telem = None
        if mesh is not None and self._shard_obs_on():
            telem = ShardStreamTelemetry(
                recorder=self.spans, mesh=mesh, query_id=self.query_id,
                bus=self.session.listeners)
        with use_shard_telemetry(telem):
            root = self._materialize_streaming(root0, mesh)
        dt = time.perf_counter() - t0
        if root is not root0:
            # chunked ingest + chunk compute happen inside the splice
            self.phase_times["streaming"] = dt
            self.spans.record("streaming", t0, t0 + dt)
        scans: List[P.LeafExec] = []
        self._collect_scans(root, scans)

        t0 = time.perf_counter()
        from . import lifecycle
        # cooperative boundary before host ingest loads the scans
        lifecycle.checkpoint("scan")
        from ..io.device_cache import load_scan
        # dedupe by node identity: a runtime filter's creation chain
        # shares its leaf with the join build side (the documented DAG),
        # so the same scan appears twice in `scans` — load and pad it
        # once, feed the same Batch to both input slots
        loaded: Dict[int, Batch] = {}
        for s in scans:
            if id(s) in loaded:
                continue
            b = load_scan(s, self._conf) \
                if isinstance(s, P.ScanExec) else s.load()
            if mesh is not None:
                from ..parallel import pad_batch_to_multiple
                b = pad_batch_to_multiple(b, int(mesh.devices.size))
            loaded[id(s)] = b
        scan_batches = [loaded[id(s)] for s in scans]
        t1 = time.perf_counter()
        self.phase_times["ingest"] = t1 - t0
        self.spans.record("ingest", t0, t1, scans=len(scans))

        t0 = time.perf_counter()
        token = None
        if mesh is not None:
            token = jnp.zeros((int(mesh.devices.size),), jnp.int32)
        # static analysis, jaxpr half: abstract-eval the exact stage
        # callable about to be jitted (gated; memoized per stage key),
        # then publish the combined findings on the bus
        self._analyze_jaxpr_phase(
            root, mesh,
            (scan_batches,) if mesh is None else (scan_batches, token))
        adaptive = bool(self._conf.get("spark_tpu.sql.adaptive.enabled"))
        profile_dir = str(self._conf.get("spark_tpu.sql.profile.dir"))
        import contextlib
        prof = jax.profiler.trace(profile_dir) if profile_dir else \
            contextlib.nullcontext()
        timeout_ms = int(self._conf.get(
            "spark_tpu.execution.stageTimeoutMs"))
        with prof:
            overflow: List[str] = []
            for _attempt in range(8):
                # failures here (compile, dispatch, trace-time injected
                # faults) propagate to _execute_recover, which classifies
                # them (execution/failures.py) and retries/degrades —
                # the unified spark.task.maxFailures seat
                t_att = time.perf_counter()
                args = (scan_batches,) if mesh is None \
                    else (scan_batches, token)
                fn = self._compile_stage(root, mesh, args)
                t_disp = time.perf_counter()
                faults.fire("stage_run")  # chaos seam: pre-dispatch
                batch, flags, metrics = fn(*args)
                # ONE batched host pull for the whole stats channel —
                # per-scalar np.asarray costs an RPC round trip each on
                # tunneled runtimes (it also syncs the attempt, making
                # the wall-clock deadline check below honest). The pull
                # is cancellable (dispatchPollMs readiness polling):
                # a cancel/deadline lands within ~one tick instead of
                # at stage completion
                flags, metrics = _sync_dispatched((flags, metrics),
                                                  self._conf)
                # jit compiles lazily: the first dispatch after a stage
                # -cache miss pays trace + XLA compile in-line, so flag
                # it — trace readers must not read that as execution
                self.spans.record(
                    "dispatch", t_disp, time.perf_counter(),
                    attempt=_attempt,
                    includes_jit_compile=getattr(
                        self, "_last_compile_was_miss", False))
                # deadline BEFORE the stage-timeout check: an attempt
                # that outran the end-to-end budget raises the
                # lifecycle error (ladder stops), never a retryable
                # StageTimeoutError — queryDeadlineMs < stageTimeoutMs
                # must not retry through the recovery ladder
                lifecycle.checkpoint("post_dispatch")
                if timeout_ms > 0:
                    att_ms = (time.perf_counter() - t_att) * 1e3
                    if att_ms > timeout_ms:
                        raise StageTimeoutError(
                            f"stage attempt took {att_ms:.0f}ms > "
                            f"stageTimeoutMs={timeout_ms}: "
                            f"{root.simple_string()}")
                overflow = [k for k, v in flags.items()
                            if k.startswith(("join_overflow_",
                                             "join_nonunique_",
                                             "join_hashsat_",
                                             "exch_overflow_",
                                             "agg_overflow_"))
                            and bool(v)]
                self._post_stage_completed(_attempt, t_att, metrics,
                                           overflow)
                if not overflow:
                    break
                self.spans.mark("aqe_overflow", flags=overflow[:8])
                # unique-build / hash-saturation fallbacks are
                # correctness re-plans, not capacity growth — never
                # gated by the adaptive conf
                if not adaptive and any(
                        not k.startswith(("join_nonunique_",
                                          "join_hashsat_"))
                        for k in overflow):
                    raise RuntimeError(
                        f"capacity overflow in {overflow} with adaptive "
                        f"re-planning disabled "
                        f"(spark_tpu.sql.adaptive.enabled=false)")
                for k in overflow:
                    if k.startswith("join_nonunique_"):
                        self._set_join_nonunique(
                            root, k[len("join_nonunique_"):])
                    elif k.startswith("join_hashsat_"):
                        self._set_join_hash_fallback(
                            root, k[len("join_hashsat_"):])
                    elif k.startswith("join_overflow_"):
                        tag = k[len("join_overflow_"):]
                        total = int(metrics[f"join_rows_{tag}"])
                        self._set_join_cap(root, tag,
                                           bucket_capacity(max(total, 8)))
                    elif k.startswith("exch_overflow_"):
                        tag = k[len("exch_overflow_"):]
                        mx = int(metrics[f"exch_max_{tag}"])
                        if self._maybe_skew_replan(root, tag, metrics,
                                                   mesh):
                            raise _ReplanRequest()
                        self._set_exchange_cap(root, tag,
                                               bucket_capacity(max(mx, 8)))
                    else:
                        tag = k[len("agg_overflow_"):]
                        total = int(metrics[f"agg_groups_{tag}"])
                        # bucketed like every other learned capacity:
                        # compute re-buckets before use, and a raw count
                        # in the stage key recompiles per exact total
                        self._set_agg_groups(root, tag,
                                             bucket_capacity(max(total, 8)))
            else:
                raise RuntimeError(
                    f"capacity retries did not converge; still "
                    f"overflowing: {overflow}")
        batch = jax.block_until_ready(batch)
        self.phase_times["execution"] = time.perf_counter() - t0
        if adaptive:
            # ROADMAP item (c): runtime-filter pruning shrinks the static
            # capacities above the filter for the NEXT execution/compile
            self._shrink_caps_from_rtf(root, metrics, mesh)
        if aqe_key is not None:
            # harvest from the UNSPLICED plan: streamed-aggregate joins
            # mutated their caps on the original nodes, which the
            # spliced `root` no longer contains. Merge (don't replace)
            # so a streamed run doesn't drop caps a whole-input run
            # learned, and bound the cache (plan strings are big).
            converged: Dict[str, int] = {}
            self._collect_caps(self.executed_plan, converged)
            self._collect_caps(root, converged)
            if converged:
                store = self.session._aqe_caps
                store.setdefault(aqe_key, {}).update(converged)
                while len(store) > 256:
                    store.pop(next(iter(store)))
        # per-shard exchange vectors ([n] arrays riding the metrics
        # channel) unpack into transfer-phase flight-recorder records;
        # they never enter last_metrics (scalar columns only)
        if mesh is not None and self._shard_obs_on():
            self._record_exchange_shards(metrics, mesh)
        # *_ms metrics are floats (sub-ms filter/table builds are the
        # common case) — int() would floor them to a useless 0
        self.last_metrics = {
            k: (round(float(v), 3)
                if k.startswith(("rtf_build_ms_", "join_build_ms_",
                                 "join_probe_ms_"))
                else int(v))
            for k, v in metrics.items()
            if not k.startswith("shard_")}
        if self._mesh_fallback:
            # degraded single-device result of a mesh-planned query:
            # visible next to the device metrics and in the event log
            self.last_metrics["mesh_fallback"] = 1
        # fill the data cache on the first action over a marked plan
        fp = self.session._plan_fingerprint(self.logical)
        if fp in self.session._cache_requests and \
                fp not in self.session._data_cache:
            self.session._data_cache[fp] = batch.to_arrow()
        self._log_event(root)
        return batch, flags, metrics

    def _maybe_skew_replan(self, root: P.PhysicalPlan, exch_tag: str,
                           metrics: Dict, mesh) -> bool:
        """On a skewed shuffle-join exchange (max bucket > factor x mean
        rows/shard), force the join to broadcast and request a re-plan
        — the `OptimizeSkewedJoin.scala:56` / `DynamicJoinSelection`
        move, expressed as strategy re-selection. Returns True when an
        override was recorded."""
        conf = self._conf
        if getattr(self, "_no_more_replans", False):
            return False  # budget exhausted: capacity growth only
        if mesh is None or not bool(conf.get(
                "spark_tpu.sql.adaptive.skewJoin.enabled")):
            return False
        n = int(mesh.devices.size)
        factor = float(conf.get("spark_tpu.sql.adaptive.skewJoin.factor"))
        limit = int(conf.get(
            "spark_tpu.sql.adaptive.skewJoin.broadcastThreshold"))
        mx = int(metrics.get(f"exch_max_{exch_tag}", 0))
        rows = int(metrics.get(f"exch_rows_{exch_tag}", 0))
        # exch_max is the max per-(src,dst) bucket count; a uniform
        # spread puts rows/n^2 in each bucket
        if rows <= 0 or mx * n * n <= factor * rows:
            return False  # overflow without skew: capacity growth wins

        # find the join fed by this exchange
        hit = []

        def walk(node, parent):
            for c in node.children:
                walk(c, node)
            if isinstance(node, P.ExchangeExec) and node.tag == exch_tag \
                    and isinstance(parent, P.JoinExec):
                hit.append(parent)

        walk(root, None)
        if not hit:
            return False
        join = hit[0]
        if join.strategy != "shuffle" or join.how in ("right", "full") \
                or join.tag in self._join_overrides:
            return False
        # measured build-side size: its own exchange's routed rows
        build = join.children[1]
        build_rows = None
        if isinstance(build, P.ExchangeExec):
            build_rows = metrics.get(f"exch_rows_{build.tag}")
        if build_rows is None:
            return False  # no measurement -> keep capacity growth
        width = 8 * max(1, len(build.schema().fields))
        if int(build_rows) * width > limit:
            return False
        self._join_overrides[join.tag] = "broadcast"
        return True

    def _shrink_caps_from_rtf(self, root: P.PhysicalPlan, metrics: Dict,
                              mesh) -> None:
        """Shrink post-filter static capacities using runtime-filter
        pruned-row counts (ROADMAP runtime-filter item (c)): the probe
        exchange's receive blocks and the guarded join's output were
        seeded from the UNPRUNED probe capacity; after a converged run,
        the survivors (rtf_tested - rtf_pruned) bound what those buffers
        ever hold, so re-seed them down for the next compile — a
        single-chip HBM/kernel-size win, not just ICI traffic. The
        measured actuals (exch_max/join_rows) floor the new value, so a
        shrunk cap never overflows on identical data; on grown data the
        AQE overflow loop corrects upward as usual. Mutates `root`, whose
        caps the AQE harvest persists."""
        from ..columnar import bucket_capacity
        n = int(mesh.devices.size) if mesh is not None else 1

        def walk(node, ancestors):
            for c in node.children:
                walk(c, ancestors + (node,))
            if not isinstance(node, P.RuntimeFilterExec):
                return
            tested = metrics.get(f"rtf_tested_{node.tag}")
            pruned = metrics.get(f"rtf_pruned_{node.tag}")
            if tested is None or pruned is None:
                return
            surv = int(tested) - int(pruned)
            if int(tested) <= 0 or int(pruned) <= 0 or surv < 0:
                return  # filter never pruned: nothing to shrink from
            # climb from the filter to the join it guards, shrinking the
            # exchange blocks on the way (narrow ops pass through)
            for anc in reversed(ancestors):
                if isinstance(anc, (P.ProjectExec, P.FilterExec,
                                    P.RuntimeFilterExec)):
                    continue
                if isinstance(anc, P.ExchangeExec):
                    if mesh is None:
                        continue  # identity on a single chip
                    actual = int(metrics.get(f"exch_max_{anc.tag}", 0))
                    new = bucket_capacity(
                        max(2 * (-(-surv // n)), actual, 8))
                    if anc.block_cap is None or new < anc.block_cap:
                        anc.block_cap = new
                    continue
                if isinstance(anc, P.JoinExec):
                    actual = int(metrics.get(f"join_rows_{anc.tag}", 0))
                    new = bucket_capacity(max(2 * surv, actual, 8))
                    if anc.out_cap is None or new < anc.out_cap:
                        anc.out_cap = new
                break  # the guarded join (or an opaque op) ends the climb

        walk(root, ())

    def _record_exchange_shards(self, metrics: Dict, mesh) -> None:
        """Unpack the exchanges' per-shard row/byte vectors (emitted as
        one-hot psums by parallel/shuffle.py) into transfer-phase shard
        records on the span recorder — the exchange half of the flight
        recorder, next to the chunk drivers' compute/ingest records."""
        from ..parallel.mesh import shard_hosts
        import numpy as np
        hosts = shard_hosts(mesh)
        for k, v in metrics.items():
            if not k.startswith("shard_rows_"):
                continue
            tag = k[len("shard_rows_"):]
            rows = np.asarray(v).reshape(-1)
            nbytes = metrics.get(f"shard_bytes_{tag}")
            nbytes = np.asarray(nbytes).reshape(-1) \
                if nbytes is not None else None
            self.spans.add_shard_records([{
                "shard": i, "host": hosts[i] if i < len(hosts) else 0,
                "chunk": None, "phase": "transfer", "rows": int(rows[i]),
                "bytes": int(nbytes[i]) if nbytes is not None else None,
                "source": f"exchange:{tag}",
            } for i in range(len(rows))])

    def _post_stage_completed(self, attempt: int, t_att: float,
                              metrics: Dict, overflow: List[str]) -> None:
        from ..observability.listener import StageCompletedEvent
        if not self._observe_events:
            return
        cost = self.stage_costs.get(self._last_stage_key or "")
        self.session.listeners.post(
            "on_stage_completed", StageCompletedEvent(
                query_id=self.query_id, ts=time.time(),
                stage_key=self._last_stage_key or "",
                key_hash=(cost or {}).get("key_hash", ""),
                attempt=attempt,
                elapsed_ms=round((time.perf_counter() - t_att) * 1e3, 2),
                metrics=metrics, overflow=list(overflow)))

    def _build_event(self, root: Optional[P.PhysicalPlan],
                     status: str = "ok", error=None) -> Dict:
        """The event-log record for this execution: one dict, JSON-line
        serializable (sinks.json_default covers numpy/JAX scalars)."""
        from ..observability import xla_cost
        from ..observability.sinks import EVENT_LOG_SCHEMA_VERSION
        event = {
            "schema_version": EVENT_LOG_SCHEMA_VERSION,
            "query_id": self.query_id,
            "ts": time.time(),
            "status": status,
            "plan": root.describe() if root is not None else
            self.logical.tree_string(),
            "phase_times_s": {k: round(v, 4)
                              for k, v in self.phase_times.items()},
            "metrics": self.last_metrics,
        }
        if error is not None:
            event["error"] = f"{type(error).__name__}: {error}"[:300]
        if root is not None:
            try:
                # runtime-annotated physical tree (rows/caps/hbm notes)
                # — the GET /queries/<id>/plan payload
                event["plan_tree"] = self._runtime_tree(root)
            except Exception:  # noqa: BLE001 — annotation is best-effort
                pass
        if self.spans.spans:
            event["spans"] = self.spans.to_dicts()
            if self.spans.dropped:
                event["spans_dropped"] = self.spans.dropped
        if self.spans.shard_records:
            # per-shard flight-recorder records (schema v3): mesh chunk
            # drivers' ingest/compute waits + exchange transfer vectors
            event["shards"] = list(self.spans.shard_records)
            if self.spans.shard_dropped:
                event["shards_dropped"] = self.spans.shard_dropped
        if self.plan_predictions:
            # planner/AQE size predictions, graded post-hoc against the
            # metrics in this same record (history.prediction_report)
            event["predictions"] = list(self.plan_predictions)
        if self.reorder_decisions is not None:
            # cost-based join-reorder decisions (plan/join_reorder.py):
            # per-region frontend order vs chosen order + estimates,
            # served by GET /queries/<id>/plan
            event["reorder"] = {
                "enabled": bool(self.session.conf.get(
                    "spark_tpu.sql.cbo.joinReorder")),
                "changed": any(d.get("changed")
                               for d in self.reorder_decisions),
                "regions": list(self.reorder_decisions)}
        if self.rule_trace:
            # per-rule optimizer application records (schema v7,
            # analysis/plan_integrity.py PlanChangeTracer): batch, rule,
            # invocations, effective count, ms, optional first-effective
            # tree diff — history.rule_report / GET /queries/<id>/plan
            event["rule_trace"] = [dict(r) for r in self.rule_trace]
        if self.stage_costs:
            # per-stage XLA cost/memory accounting (history.hbm_summary
            # / compile_summary read these)
            event["stages"] = list(self.stage_costs.values())
            cap = xla_cost.device_hbm_capacity()
            if cap is not None:
                event["device_hbm_capacity_bytes"] = cap
        if self.analysis_findings:
            # pre-compile analyzer findings (read back via
            # history.read_event_log; bench counts them per query)
            event["analysis_findings"] = [
                f.to_dict() for f in self.analysis_findings]
        if self.udf_summary:
            # python-UDF lane record (schema v5): mode + batch/row
            # totals + worker restarts (history.prediction_report
            # grades udf_batches/udf_rows predictions against these)
            event["udf"] = dict(self.udf_summary)
        if self.fault_summary:
            # every retry/eviction/degradation/fallback this
            # execution survived (history.fault_summary reads these)
            event["fault_summary"] = dict(
                self.fault_summary,
                retry_backoff_ms=round(
                    self._retry_policy.total_sleep_ms, 1)
                if self._retry_policy is not None else 0.0,
                events=self.fault_events)
        return event

    def _post_query_end(self, root: Optional[P.PhysicalPlan],
                        status: str = "ok", error=None) -> None:
        from ..observability.listener import QueryEndEvent
        if not self._observe_events:
            return
        try:
            event = self._build_event(root, status, error)
        except Exception as e:  # noqa: BLE001 — observability only
            import warnings
            warnings.warn(f"event build failed: {e}")
            return
        self.session.listeners.post("on_query_end", QueryEndEvent(
            query_id=self.query_id, ts=event["ts"], status=status,
            event=event, spans=self.spans))

    def _log_event(self, root: P.PhysicalPlan) -> None:
        """Publish the execution's event record on the listener bus
        (the `EventLoggingListener.scala:50` event-stream analog — the
        JSONL writer, Chrome-trace writer, and metrics sinks are all
        subscribers; replay with spark_tpu.history.read_event_log)."""
        self._post_query_end(root, status="ok")

    def collect(self) -> pa.Table:
        # ONE arbiter lease scope spans the external-collect gate AND
        # the execute_batch that runs when the gate says "fits
        # resident": the residency lease granted during the gate check
        # must stay held while the resident execution actually uses the
        # bytes (the inner enter_query calls nest onto this owner).
        from ..service import arbiter as res_arbiter
        from . import lifecycle
        arb_token = res_arbiter.enter_query(
            f"{self.session.app_id}:q{self.query_id}")
        # lifecycle scope spans the external-collect gate too, so a
        # cancel lands between chunks of the out-of-core egress path
        # (execute_batch nests inside this scope, sharing the token)
        lc_scope = lifecycle.enter_query_scope(
            self.session.app_id, self.query_id, self.session.conf)
        try:
            try:
                ext = self._try_external_collect()
            except (lifecycle.QueryCancelledError,
                    lifecycle.QueryDeadlineError) as e:
                # the external path never reaches execute_batch's
                # except: observe here (counter + fault record + event)
                self._observe_cancel(e)
                raise
            if ext is not None:
                return ext
            batch, _, _ = self.execute_batch()
            return batch.to_arrow()
        finally:
            lifecycle.exit_query_scope(lc_scope)
            res_arbiter.exit_query(arb_token)

    def _try_external_collect(self) -> Optional[pa.Table]:
        """Out-of-core host egress (execution/external.py): ORDER BY /
        LIMIT / plain materialization over scans past the device budget
        — per-query deviceBudget, or the shared arbiter pool when the
        service installed one — stream chunk-wise and spill to host
        Arrow, never resident."""
        from ..service import arbiter as res_arbiter
        if not res_arbiter.out_of_core_active(self.session.conf):
            return None
        import warnings
        from ..testing import faults
        from .external import try_external_collect
        from .failures import FailureClass, RetryPolicy, classify
        from .python_eval import plan_has_udfs
        from .recovery import RecoveryContext
        self._activate_conf()
        if plan_has_udfs(self.executed_plan):
            return None  # UDF stages evaluate through execute_batch
        # the out-of-core egress path never reaches execute_batch, but
        # it is exactly where the host-spill findings live — analyze
        # (and strict-gate) here too
        self._observe_events = self._events_enabled()
        self._analyze_plan_phase()
        self._post_analysis(self._analysis_conf()[1])
        # chunk-granular retry covers this path too: arm conf-driven
        # injection and record chunk_retry actions on THIS execution
        # (counters reset like execute_batch — repeated collects must
        # not accumulate stale actions)
        faults.arm(self.session.conf)
        self.fault_summary = {}
        self.fault_events = []
        self._recovery = RecoveryContext(metrics=self.session.metrics,
                                         record=self._record_fault)
        t0 = time.perf_counter()
        conf = self.session.conf
        # transient rung for the egress path (the execute_batch ladder
        # never sees these streams): a flake that exhausts the
        # per-chunk budget restarts the whole external stream under
        # the same maxRetries/backoff budget instead of aborting
        policy = RetryPolicy(
            max_retries=self._max_retries(conf),
            backoff_ms=float(conf.get("spark_tpu.execution.backoffMs")))
        arb_token = res_arbiter.enter_query(
            f"{self.session.app_id}:q{self.query_id}:ext")
        try:
            while True:
                try:
                    out = try_external_collect(
                        self.session, self.executed_plan, conf,
                        self.session._stage_cache, self._recovery)
                    break
                except Exception as e:  # noqa: BLE001 — classified below
                    if classify(e) not in (FailureClass.TRANSIENT,
                                           FailureClass.TIMEOUT):
                        raise
                    slept = policy.attempt_retry()
                    if slept is None:
                        raise
                    warnings.warn(
                        f"transient stage failure, retrying external "
                        f"collect ({policy.remaining} left, backoff "
                        f"{slept:.0f}ms): {type(e).__name__}: "
                        f"{str(e)[:160]}")
                    self._record_fault("transient_retry", e,
                                       backoff_ms=round(slept, 1))
                    self._recovery.begin_recovery_attempt()
        finally:
            self._recovery.release()
            res_arbiter.exit_query(arb_token)
        if out is not None:
            self.phase_times["external"] = time.perf_counter() - t0
        return out

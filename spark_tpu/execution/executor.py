"""Query execution driver.

Mirrors the reference's `execution/QueryExecution.scala` phase pipeline
(analyzed -> optimizedPlan -> sparkPlan -> executedPlan -> toRdd), except
the terminal artifact is a single jitted stage function over columnar
Batches instead of an RDD DAG: XLA compilation replaces both Janino
whole-stage codegen and task scheduling for the single-chip path. The
compiled-stage cache keyed on the physical plan fingerprint is the analog
of `CodeGenerator.compile:1435`'s Janino cache.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar import Batch
from ..config import Conf
from ..plan import logical as L
from ..plan import physical as P
from ..plan.optimizer import default_optimizer
from ..plan.planner import plan_physical


class _ReplanRequest(Exception):
    """Internal: restart execution after a strategy re-plan."""


class QueryExecution:
    def __init__(self, session, logical: L.LogicalPlan):
        self.session = session
        self.logical = logical
        self._analyzed: Optional[L.LogicalPlan] = None
        self._optimized: Optional[L.LogicalPlan] = None
        self._executed: Optional[P.PhysicalPlan] = None
        self.phase_times: Dict[str, float] = {}
        self.last_metrics: Dict[str, float] = {}  # ints except rtf_build_ms_*
        self.spilled_partial_rows: Optional[int] = None
        # adaptive strategy re-plans (DynamicJoinSelection.scala:1):
        # {join_tag: strategy}, applied by executed_plan on re-plan
        self._join_overrides: Dict[str, str] = {}

    def _activate_conf(self) -> None:
        """Apply session conf to analysis-time globals (the reference's
        SQLConf thread-activation; the driver is single-threaded)."""
        from .. import expr as expr_mod
        expr_mod.CASE_SENSITIVE = bool(
            self.session.conf.get("spark_tpu.sql.caseSensitive"))

    @property
    def analyzed(self) -> L.LogicalPlan:
        if self._analyzed is None:
            t0 = time.perf_counter()
            self._activate_conf()
            self.logical.schema()  # eager name/type resolution raises here
            self._analyzed = self.logical
            self.phase_times["analysis"] = time.perf_counter() - t0
        return self._analyzed

    def _apply_cache(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        """Substitute cached subtrees with scans over their materialized
        tables (reference: CacheManager.useCachedData). A MARKED but
        not-yet-materialized subtree appearing in any query materializes
        on first use, like the reference's InMemoryRelation. Matching is
        on the pre-optimization plan fingerprint."""
        session = self.session
        if not session._data_cache and not session._cache_requests:
            return plan
        root_fp = session._plan_fingerprint(plan)

        def f(node):
            fp = session._plan_fingerprint(node)
            table = session._data_cache.get(fp)
            if table is None and fp in session._cache_requests \
                    and fp != root_fp:
                # first use inside a larger query: materialize now (the
                # fp != root_fp guard leaves root execution to the
                # normal path, which fills the cache afterwards)
                sub = QueryExecution(session, session._cache_requests[fp])
                table = sub.collect()
                session._data_cache[fp] = table
            if table is not None:
                from ..io.sources import ArrowTableSource
                return L.Scan(ArrowTableSource("__cached__", table))
            return None

        # top-down so the largest cached subtree wins
        return plan.transform_down(f)

    def _resolve_scalar_subqueries(self, plan: L.LogicalPlan
                                   ) -> L.LogicalPlan:
        """Execute uncorrelated scalar subqueries and substitute their
        single value as a Literal — BEFORE optimization so the literal
        participates in pushdown (reference: PlanSubqueries +
        ScalarSubquery execution)."""
        from ..expr import Literal

        def expr_has(e) -> bool:
            if isinstance(e, L.ScalarSubqueryExpr):
                return True
            return any(expr_has(c) for c in e.children)

        if not any(expr_has(e) for e in L.iter_expressions(plan)):
            return plan  # skip the rebuild on the no-subquery hot path

        def fix(e):
            def f(node):
                if isinstance(node, L.ScalarSubqueryExpr):
                    if len(node.plan.schema().fields) != 1:
                        raise RuntimeError(
                            "scalar subquery must return exactly one "
                            "column")
                    table = QueryExecution(self.session,
                                           node.plan).collect()
                    if table.num_rows > 1:
                        raise RuntimeError(
                            "scalar subquery returned more than one row")
                    dt = node.plan.schema().fields[0].dtype
                    val = None if table.num_rows == 0 else \
                        table.column(0)[0].as_py()
                    return Literal(val, dt)
                return node
            return e.transform_up(f)

        return L.map_expressions(plan, fix)

    @property
    def optimized_plan(self) -> L.LogicalPlan:
        if self._optimized is None:
            t0 = time.perf_counter()
            plan = self._apply_cache(self.analyzed)
            plan = self._resolve_scalar_subqueries(plan)
            self._optimized = default_optimizer().execute(plan)
            self.phase_times["optimization"] = time.perf_counter() - t0
        return self._optimized

    @property
    def executed_plan(self) -> P.PhysicalPlan:
        if self._executed is None:
            t0 = time.perf_counter()
            self._executed = plan_physical(
                self.optimized_plan, self.session.conf,
                join_strategy_overrides=self._join_overrides or None)
            self.phase_times["planning"] = time.perf_counter() - t0
        return self._executed

    def explain(self, extended: bool = False, runtime: bool = False) -> str:
        out = []
        if extended:
            out += ["== Logical Plan ==", self.logical.tree_string(),
                    "== Optimized Logical Plan ==",
                    self.optimized_plan.tree_string()]
        if runtime and self.last_metrics:
            out.append("== Physical Plan (runtime metrics) ==")
            out.append(self._runtime_tree(self.executed_plan))
        else:
            out += ["== Physical Plan ==",
                    self.executed_plan.tree_string()]
        return "\n".join(out)

    def _runtime_tree(self, node: P.PhysicalPlan, depth: int = 0) -> str:
        """Tree annotated with per-operator output rows (the SQL-UI plan
        graph analog of `metric/SQLMetrics.scala:40`)."""
        rows = self.last_metrics.get(f"rows_{getattr(node, 'op_tag', '')}")
        note = f"   [rows out: {rows:,}]" if rows is not None else ""
        line = "  " * depth + node.simple_string() + note
        return "\n".join([line] + [self._runtime_tree(c, depth + 1)
                                   for c in node.children])

    # -- execution ----------------------------------------------------------

    def _collect_scans(self, node: P.PhysicalPlan,
                       out: List[P.LeafExec]) -> None:
        if getattr(node, "needs_input", False):
            out.append(node)
        for c in node.children:
            self._collect_scans(c, out)

    def _materialize_streaming(self, node: P.PhysicalPlan,
                               mesh=None) -> P.PhysicalPlan:
        """Execute streamable aggregates eagerly (chunked, accumulator
        carry) and splice their results back as InputExec leaves. Under a
        mesh, PARTIAL aggregates over chunked scans stream with per-shard
        tables (the exchange + final stages above run unchanged)."""
        from .streaming_agg import (stream_scan_aggregate_mesh,
                                    try_stream_aggregate,
                                    try_stream_aggregate_spill)
        if mesh is None and isinstance(node, P.HashAggregateExec):
            result = try_stream_aggregate(node, self.session.conf,
                                          self.session._stage_cache)
            if result is not None:
                return P.InputExec(result, node.schema(), label="streamed_agg")
            spill = try_stream_aggregate_spill(node, self.session.conf,
                                               self.session._stage_cache)
            if spill is not None:
                # out-of-core: host-spilled partials re-reduce in a
                # FINAL aggregate (the partial -> exchange -> final
                # split of AggUtils.scala, with host Arrow buffers in
                # the exchange's seat)
                from ..expr import ColumnRef
                partial_table, partial_node = spill
                inp = P.InputExec(Batch.from_arrow(partial_table),
                                  partial_node.schema(),
                                  label="spilled_partials")
                inp._agg_base_schema = node._base_schema()
                final_groups = [ColumnRef(g.name())
                                for g in node.group_exprs]
                final = P.HashAggregateExec(
                    inp, final_groups, node.agg_exprs, mode="final",
                    est_groups=max(partial_table.num_rows, 8))
                final.tag = node.tag
                self.spilled_partial_rows = partial_table.num_rows
                return final
        if mesh is not None and isinstance(node, P.HashAggregateExec) \
                and node.mode == "partial":
            result = stream_scan_aggregate_mesh(
                node, mesh, self.session.conf, self.session._stage_cache)
            if result is not None:
                spliced = P.InputExec(result, node.schema(),
                                      label="streamed_partial_agg")
                # the final aggregate above resolves its functions
                # against the PRE-aggregation schema
                spliced._agg_base_schema = node._base_schema()
                return spliced
        new_children = tuple(self._materialize_streaming(c, mesh)
                             for c in node.children)
        if new_children != node.children:
            import copy
            node = copy.copy(node)
            node.children = new_children
        return node

    def _materialize_generates(self, node: P.PhysicalPlan
                               ) -> P.PhysicalPlan:
        """Mesh runs: offsets-encoded list columns cannot shard (their
        offsets are absolute into the flattened values), so explode
        subtrees materialize single-device and the FLAT exploded result
        shards as an InputExec — the stage cut the reference makes at
        GenerateExec.scala:1, with the generate on the driver device."""
        new_children = tuple(self._materialize_generates(c)
                             for c in node.children)
        if new_children != node.children:
            import copy
            node = copy.copy(node)
            node.children = new_children
        if isinstance(node, P.GenerateExec):
            from .streaming_agg import _materialize_subtree
            b = _materialize_subtree(node, self.session.conf)
            return P.InputExec(b, node.schema(), label="generated")
        return node

    def _stage_key(self, root: P.PhysicalPlan, mesh=None) -> str:
        conf = self.session.conf
        n = int(mesh.devices.size) if mesh is not None else 1
        metrics_on = bool(conf.get("spark_tpu.sql.metrics.enabled"))
        return (root.describe()
                + (f"#mesh{n}" if mesh is not None else "")
                + f"#m{int(metrics_on)}")

    def _compile_stage(self, root: P.PhysicalPlan, mesh=None):
        conf = self.session.conf
        key = self._stage_key(root, mesh)
        fn = self.session._stage_cache.get(key)
        if fn is not None:
            return fn

        per_op = bool(conf.get("spark_tpu.sql.metrics.enabled"))

        def replay_root(ctx, inputs):
            counter = [0]

            def replay(node: P.PhysicalPlan) -> Batch:
                if getattr(node, "needs_input", False):
                    b = inputs[counter[0]]
                    counter[0] += 1
                    return b
                child_batches = [replay(c) for c in node.children]
                out = node.compute(ctx, child_batches)
                if per_op:
                    # rows-out per operator, psum'd across shards — the
                    # SQLMetrics.scala:40 analog, shown by
                    # explain(runtime=True)
                    ctx.add_metric(
                        f"rows_{getattr(node, 'op_tag', 'op?')}",
                        jnp.sum(out.selection_mask().astype(jnp.int64)))
                return out

            return replay(root)

        if mesh is None:
            def run(inputs):
                ctx = P.ExecContext(conf)
                out = replay_root(ctx, inputs)
                return out, ctx.flags, ctx.metrics

            fn = jax.jit(run)
        else:
            from jax.sharding import PartitionSpec as Psp
            from ..parallel.mesh import shard_map
            from ..parallel import stripe_batch
            from ..parallel.mesh import AXIS

            n = int(mesh.devices.size)

            # sorted/limited/global-agg results are replicated on every
            # shard; each shard emits its contiguous stripe so the
            # out_spec reassembles the full (ordered) result exactly once
            replicated_out = isinstance(
                root.output_partitioning(),
                (P.SinglePartition, P.Replicated))

            def run_shard(inputs, _token):
                ctx = P.ExecContext(conf, axis_name=AXIS, n_shards=n)
                out = replay_root(ctx, inputs)
                if replicated_out:
                    out = stripe_batch(out, ctx)
                # AQE stats channel: reduce flags/metrics to replicated
                # scalars (pmax for per-shard capacity stats, psum else)
                flags = {k: jax.lax.psum(
                    jnp.asarray(v).astype(jnp.int32), AXIS)
                    for k, v in ctx.flags.items()}
                metrics = {}
                for k, v in ctx.metrics.items():
                    # capacity-sizing stats take the worst shard (pmax);
                    # row counts sum across shards
                    red = jax.lax.pmax if k.startswith(
                        ("join_rows_", "exch_max_", "agg_groups_",
                         "rtf_build_ms_")) \
                        else jax.lax.psum
                    metrics[k] = red(jnp.asarray(v), AXIS)
                return out, flags, metrics

            fn = jax.jit(shard_map(
                run_shard, mesh=mesh,
                in_specs=(Psp(AXIS), Psp(AXIS)),
                out_specs=(Psp(AXIS), Psp(), Psp()),
                check_vma=False))
        self.session._stage_cache[key] = fn
        return fn

    def _aqe_cache_key(self, mesh) -> Optional[str]:
        """Plan + data-identity key for persisted AQE capacities; None
        (uncacheable) when any scan's source has no identity stamp."""
        tokens = [s.source.cache_token()
                  for s in L.iter_scans(self.optimized_plan)]
        if any(t is None for t in tokens):
            return None
        n = int(mesh.devices.size) if mesh is not None else 1
        return (self.optimized_plan.tree_string()
                + f"#mesh{n}#src{tokens!r}")

    @staticmethod
    def _collect_caps(root: P.PhysicalPlan, out: Dict[str, int]) -> None:
        """Harvest every AQE-discovered static capacity from a converged
        plan, keyed `kind:tag` (the persistence side of the stats
        channel: the reference re-learns MapOutputStatistics per query,
        but its shuffle files are sized dynamically — XLA's static
        shapes make remembering converged capacities the difference
        between one compile and a compile per retry per execution)."""
        for c in root.children:
            QueryExecution._collect_caps(c, out)
        if isinstance(root, P.JoinExec):
            if root.out_cap is not None:
                out[f"join:{root.tag}"] = root.out_cap
            if root.unique_build is False:
                out[f"uniq:{root.tag}"] = 0
        elif isinstance(root, P.ExchangeExec) and root.block_cap is not None:
            out[f"exch:{root.tag}"] = root.block_cap
        elif isinstance(root, P.HashAggregateExec) and root.est_groups:
            out[f"agg:{root.tag}"] = root.est_groups

    def _apply_saved_caps(self, root: P.PhysicalPlan, caps: Dict[str, int]
                          ) -> None:
        for key, cap in caps.items():
            kind, tag = key.split(":", 1)
            if kind == "join":
                self._set_join_cap(root, tag, cap)
            elif kind == "uniq":
                self._set_join_nonunique(root, tag)
            elif kind == "exch":
                self._set_exchange_cap(root, tag, cap)
            else:
                self._set_agg_groups(root, tag, cap)

    @staticmethod
    def _set_join_cap(root: P.PhysicalPlan, tag: str, cap: int) -> None:
        for c in root.children:
            QueryExecution._set_join_cap(c, tag, cap)
        if isinstance(root, P.JoinExec) and root.tag == tag:
            root.out_cap = cap

    @staticmethod
    def _set_join_nonunique(root: P.PhysicalPlan, tag: str) -> None:
        for c in root.children:
            QueryExecution._set_join_nonunique(c, tag)
        if isinstance(root, P.JoinExec) and root.tag == tag:
            root.unique_build = False

    @staticmethod
    def _set_exchange_cap(root: P.PhysicalPlan, tag: str, cap: int) -> None:
        for c in root.children:
            QueryExecution._set_exchange_cap(c, tag, cap)
        if isinstance(root, P.ExchangeExec) and root.tag == tag:
            root.block_cap = cap

    @staticmethod
    def _set_agg_groups(root: P.PhysicalPlan, tag: str, est: int) -> None:
        for c in root.children:
            QueryExecution._set_agg_groups(c, tag, est)
        if isinstance(root, P.HashAggregateExec) and root.tag == tag:
            root.est_groups = est

    def execute_batch(self) -> Tuple[Batch, Dict, Dict]:
        """Run the query, returning (device Batch, flags, metrics).

        Joins whose many-to-many expansion overflows the seeded output
        capacity surface a `join_overflow_<tag>` flag plus the true row
        total in `join_rows_<tag>`; the loop below re-jits those joins
        with a sufficient static capacity (the AQE-style stats->re-plan
        host loop, `AdaptiveSparkPlanExec.scala:64`). A skewed shuffle
        join raises _ReplanRequest instead: the physical plan rebuilds
        with the join forced to broadcast and execution restarts."""
        from ..columnar import bucket_capacity
        from ..parallel.mesh import get_mesh
        self._activate_conf()
        self.session._exec_depth += 1
        try:
            for _replan in range(4):
                try:
                    return self._execute_batch_inner()
                except _ReplanRequest:
                    self._executed = None  # re-plan with _join_overrides
            # replan budget exhausted: finish with capacity growth only
            self._no_more_replans = True
            return self._execute_batch_inner()
        finally:
            self.session._exec_depth -= 1
            if self.session._exec_depth == 0:
                # implicit (WITH-clause) materializations are statement
                # -scoped: evict when the outermost execution finishes
                self.session._evict_implicit_caches()

    def _execute_batch_inner(self) -> Tuple[Batch, Dict, Dict]:
        from ..columnar import bucket_capacity
        from ..parallel.mesh import get_mesh
        mesh = get_mesh(self.session.conf)
        # seed capacities a previous execution of this plan discovered,
        # so repeated queries skip the overflow->re-jit ramp entirely.
        # The key includes every scan's source identity stamp: caps
        # learned on old data must not seed (possibly too small) after a
        # table is re-registered or a file rewritten.
        aqe_key = self._aqe_cache_key(mesh)
        saved_caps = self.session._aqe_caps.get(aqe_key) \
            if aqe_key is not None else None
        if saved_caps:
            self._apply_saved_caps(self.executed_plan, saved_caps)
        root0 = self.executed_plan
        from .python_eval import extract_python_udfs, plan_has_udfs
        if plan_has_udfs(root0):
            t0 = time.perf_counter()
            root0 = extract_python_udfs(root0, self.session.conf)
            self.phase_times["python_udfs"] = time.perf_counter() - t0
        if mesh is not None:
            root0 = self._materialize_generates(root0)
        t0 = time.perf_counter()
        root = self._materialize_streaming(root0, mesh)
        dt = time.perf_counter() - t0
        if root is not root0:
            # chunked ingest + chunk compute happen inside the splice
            self.phase_times["streaming"] = dt
        scans: List[P.LeafExec] = []
        self._collect_scans(root, scans)

        t0 = time.perf_counter()
        from ..io.device_cache import load_scan
        # dedupe by node identity: a runtime filter's creation chain
        # shares its leaf with the join build side (the documented DAG),
        # so the same scan appears twice in `scans` — load and pad it
        # once, feed the same Batch to both input slots
        loaded: Dict[int, Batch] = {}
        for s in scans:
            if id(s) in loaded:
                continue
            b = load_scan(s, self.session.conf) \
                if isinstance(s, P.ScanExec) else s.load()
            if mesh is not None:
                from ..parallel import pad_batch_to_multiple
                b = pad_batch_to_multiple(b, int(mesh.devices.size))
            loaded[id(s)] = b
        scan_batches = [loaded[id(s)] for s in scans]
        self.phase_times["ingest"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        token = None
        if mesh is not None:
            token = jnp.zeros((int(mesh.devices.size),), jnp.int32)
        adaptive = bool(self.session.conf.get("spark_tpu.sql.adaptive.enabled"))
        profile_dir = str(self.session.conf.get("spark_tpu.sql.profile.dir"))
        import contextlib
        prof = jax.profiler.trace(profile_dir) if profile_dir else \
            contextlib.nullcontext()
        max_fail = int(self.session.conf.get(
            "spark_tpu.sql.execution.maxTaskFailures"))
        transient_left = max(0, max_fail)
        with prof:
            overflow: List[str] = []
            for _attempt in range(8):
                # transient infra failures (remote-compile 500s on
                # tunneled runtimes, UNAVAILABLE) retry with a fresh
                # compile in their OWN loop — the spark.task.maxFailures
                # analog; they never consume capacity-replan iterations
                while True:
                    fn = self._compile_stage(root, mesh)
                    try:
                        if mesh is None:
                            batch, flags, metrics = fn(scan_batches)
                        else:
                            batch, flags, metrics = fn(scan_batches,
                                                       token)
                        break
                    except Exception as e:  # noqa: BLE001
                        msg = f"{type(e).__name__}: {e}"
                        transient = any(t in msg for t in (
                            "remote_compile", "UNAVAILABLE",
                            "DEADLINE_EXCEEDED"))
                        if not transient or transient_left <= 0:
                            raise
                        transient_left -= 1
                        import warnings
                        warnings.warn(
                            f"transient stage failure, retrying "
                            f"({transient_left} left): {msg[:160]}")
                        # evict only THIS stage's compiled entry
                        self.session._stage_cache.pop(
                            self._stage_key(root, mesh), None)
                # ONE batched host pull for the whole stats channel —
                # per-scalar np.asarray costs an RPC round trip each on
                # tunneled runtimes
                flags, metrics = jax.device_get((flags, metrics))
                overflow = [k for k, v in flags.items()
                            if k.startswith(("join_overflow_",
                                             "join_nonunique_",
                                             "exch_overflow_",
                                             "agg_overflow_"))
                            and bool(v)]
                if not overflow:
                    break
                # unique-build fallback is a correctness re-plan, not a
                # capacity growth — never gated by the adaptive conf
                if not adaptive and any(
                        not k.startswith("join_nonunique_")
                        for k in overflow):
                    raise RuntimeError(
                        f"capacity overflow in {overflow} with adaptive "
                        f"re-planning disabled "
                        f"(spark_tpu.sql.adaptive.enabled=false)")
                for k in overflow:
                    if k.startswith("join_nonunique_"):
                        self._set_join_nonunique(
                            root, k[len("join_nonunique_"):])
                    elif k.startswith("join_overflow_"):
                        tag = k[len("join_overflow_"):]
                        total = int(metrics[f"join_rows_{tag}"])
                        self._set_join_cap(root, tag,
                                           bucket_capacity(max(total, 8)))
                    elif k.startswith("exch_overflow_"):
                        tag = k[len("exch_overflow_"):]
                        mx = int(metrics[f"exch_max_{tag}"])
                        if self._maybe_skew_replan(root, tag, metrics,
                                                   mesh):
                            raise _ReplanRequest()
                        self._set_exchange_cap(root, tag,
                                               bucket_capacity(max(mx, 8)))
                    else:
                        tag = k[len("agg_overflow_"):]
                        total = int(metrics[f"agg_groups_{tag}"])
                        self._set_agg_groups(root, tag, max(total, 8))
            else:
                raise RuntimeError(
                    f"capacity retries did not converge; still "
                    f"overflowing: {overflow}")
        batch = jax.block_until_ready(batch)
        self.phase_times["execution"] = time.perf_counter() - t0
        if aqe_key is not None:
            # harvest from the UNSPLICED plan: streamed-aggregate joins
            # mutated their caps on the original nodes, which the
            # spliced `root` no longer contains. Merge (don't replace)
            # so a streamed run doesn't drop caps a whole-input run
            # learned, and bound the cache (plan strings are big).
            converged: Dict[str, int] = {}
            self._collect_caps(self.executed_plan, converged)
            self._collect_caps(root, converged)
            if converged:
                store = self.session._aqe_caps
                store.setdefault(aqe_key, {}).update(converged)
                while len(store) > 256:
                    store.pop(next(iter(store)))
        # rtf_build_ms_* is a float (sub-ms filter builds are the
        # common case) — int() would floor it to a useless 0
        self.last_metrics = {
            k: (round(float(v), 3) if k.startswith("rtf_build_ms_")
                else int(v))
            for k, v in metrics.items()}
        # fill the data cache on the first action over a marked plan
        fp = self.session._plan_fingerprint(self.logical)
        if fp in self.session._cache_requests and \
                fp not in self.session._data_cache:
            self.session._data_cache[fp] = batch.to_arrow()
        self._log_event(root)
        return batch, flags, metrics

    def _maybe_skew_replan(self, root: P.PhysicalPlan, exch_tag: str,
                           metrics: Dict, mesh) -> bool:
        """On a skewed shuffle-join exchange (max bucket > factor x mean
        rows/shard), force the join to broadcast and request a re-plan
        — the `OptimizeSkewedJoin.scala:56` / `DynamicJoinSelection`
        move, expressed as strategy re-selection. Returns True when an
        override was recorded."""
        conf = self.session.conf
        if getattr(self, "_no_more_replans", False):
            return False  # budget exhausted: capacity growth only
        if mesh is None or not bool(conf.get(
                "spark_tpu.sql.adaptive.skewJoin.enabled")):
            return False
        n = int(mesh.devices.size)
        factor = float(conf.get("spark_tpu.sql.adaptive.skewJoin.factor"))
        limit = int(conf.get(
            "spark_tpu.sql.adaptive.skewJoin.broadcastThreshold"))
        mx = int(metrics.get(f"exch_max_{exch_tag}", 0))
        rows = int(metrics.get(f"exch_rows_{exch_tag}", 0))
        # exch_max is the max per-(src,dst) bucket count; a uniform
        # spread puts rows/n^2 in each bucket
        if rows <= 0 or mx * n * n <= factor * rows:
            return False  # overflow without skew: capacity growth wins

        # find the join fed by this exchange
        hit = []

        def walk(node, parent):
            for c in node.children:
                walk(c, node)
            if isinstance(node, P.ExchangeExec) and node.tag == exch_tag \
                    and isinstance(parent, P.JoinExec):
                hit.append(parent)

        walk(root, None)
        if not hit:
            return False
        join = hit[0]
        if join.strategy != "shuffle" or join.how in ("right", "full") \
                or join.tag in self._join_overrides:
            return False
        # measured build-side size: its own exchange's routed rows
        build = join.children[1]
        build_rows = None
        if isinstance(build, P.ExchangeExec):
            build_rows = metrics.get(f"exch_rows_{build.tag}")
        if build_rows is None:
            return False  # no measurement -> keep capacity growth
        width = 8 * max(1, len(build.schema().fields))
        if int(build_rows) * width > limit:
            return False
        self._join_overrides[join.tag] = "broadcast"
        return True

    def _log_event(self, root: P.PhysicalPlan) -> None:
        """Append one JSON line per execution when eventLog.dir is set
        (the `EventLoggingListener.scala:50` event-stream analog; replay
        with spark_tpu.history.read_event_log)."""
        log_dir = str(self.session.conf.get("spark_tpu.sql.eventLog.dir"))
        if not log_dir:
            return
        import json
        import os
        try:
            os.makedirs(log_dir, exist_ok=True)
            event = {
                "ts": time.time(),
                "plan": root.describe(),
                "phase_times_s": {k: round(v, 4)
                                  for k, v in self.phase_times.items()},
                "metrics": self.last_metrics,
            }
            path = os.path.join(log_dir, f"app-{os.getpid()}.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(event) + "\n")
        except OSError as e:
            # never fail a completed query over observability I/O
            # (the reference's listener logs and continues likewise)
            import warnings
            warnings.warn(f"event log write failed: {e}")

    def collect(self) -> pa.Table:
        ext = self._try_external_collect()
        if ext is not None:
            return ext
        batch, _, _ = self.execute_batch()
        return batch.to_arrow()

    def _try_external_collect(self) -> Optional[pa.Table]:
        """Out-of-core host egress (execution/external.py): ORDER BY /
        LIMIT / plain materialization over scans past the deviceBudget
        stream chunk-wise and spill to host Arrow — never resident."""
        budget = int(self.session.conf.get(
            "spark_tpu.sql.memory.deviceBudget"))
        if budget <= 0:
            return None
        from .external import try_external_collect
        from .python_eval import plan_has_udfs
        self._activate_conf()
        if plan_has_udfs(self.executed_plan):
            return None  # UDF stages evaluate through execute_batch
        t0 = time.perf_counter()
        out = try_external_collect(self.session, self.executed_plan,
                                   self.session.conf,
                                   self.session._stage_cache)
        if out is not None:
            self.phase_times["external"] = time.perf_counter() - t0
        return out

"""Structured failure taxonomy + retry policy for stage execution.

The reference's TaskScheduler distinguishes failure kinds and reacts per
kind — transient task failures retry (`TaskSetManager.scala:1`,
spark.task.maxFailures), fetch failures resubmit the parent stage
(`DAGScheduler.scala:1`), OOM kills spill and re-execute. XLA collapses
all of that into one opaque exception channel; this module restores the
structure:

- TRANSIENT: infra flakes (remote-compile 500s, UNAVAILABLE,
  DEADLINE_EXCEEDED) — retried with exponential backoff + jitter
  (`spark_tpu.execution.{maxRetries,backoffMs}`).
- TIMEOUT: a stage blew its wall-clock deadline
  (`spark_tpu.execution.stageTimeoutMs`) — retried like TRANSIENT
  (a fresh compile/run often clears a wedged runtime).
- OOM: HBM RESOURCE_EXHAUSTED — handled by the executor's degradation
  ladder (evict device cache -> reroute through the host-spill chunked
  path -> diagnostic raise), the UnifiedMemoryManager
  evict-then-spill discipline with host RAM as the spill tier.
- OVERFLOW: static-capacity overflow. Never an exception — it flows as
  flags through the stats channel into the AQE re-jit loop; listed here
  so the taxonomy is total.
- CANCELLED: lifecycle control (execution/lifecycle.py) — the query
  was cancelled or blew its end-to-end queryDeadlineMs. NEVER retried,
  never degraded: the recovery ladder re-raises immediately (a
  deadline blown mid-recovery must stop the ladder, not retry through
  it).
- FATAL: everything else — surfaces immediately.

Synthetic faults from `spark_tpu.testing.faults` carry their class on
the exception; real errors classify by message tokens, so both flow
through one path.
"""

from __future__ import annotations

import random
from enum import Enum
from typing import Optional


class FailureClass(Enum):
    TRANSIENT = "transient"
    TIMEOUT = "timeout"
    OOM = "oom"
    OVERFLOW = "overflow"
    CANCELLED = "cancelled"
    FATAL = "fatal"


class StageTimeoutError(RuntimeError):
    """A stage attempt exceeded spark_tpu.execution.stageTimeoutMs."""


class StageOOMError(RuntimeError):
    """RESOURCE_EXHAUSTED survived the whole degradation ladder; the
    message names the stage and its capacity stats."""


#: message tokens marking retryable infra flakes (remote-compile 500s on
#: tunneled runtimes, gRPC channel errors); DEADLINE_EXCEEDED is the
#: runtime's own deadline, distinct from our stage wall-clock TIMEOUT
_TRANSIENT_TOKENS = (
    "remote_compile", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "Connection reset", "Socket closed", "connection attempt",
)

_OOM_TOKENS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
    "Allocator ran out", "OOM while allocating",
)

#: tokens that mark a failure as coming from the COLLECTIVE path at
#: run/trace time — with meshFallback.enabled the executor re-plans
#: single-device. Deliberately narrow: a bare "mesh" token would also
#: swallow get_mesh's pre-dispatch misconfiguration diagnostic
#: ("mesh.size=N but only M devices visible"), silently degrading a
#: setup error the user needs to see.
_MESH_TOKENS = (
    "shard_map", "all_to_all", "all_gather", "collective", "axis_index",
    "NCCL",
)


def classify(exc: BaseException) -> FailureClass:
    """Map an exception to its failure class. Synthetic faults classify
    by their carried class; real errors by message tokens."""
    from ..testing.faults import FaultInjected
    from .lifecycle import QueryCancelledError, QueryDeadlineError
    if isinstance(exc, (QueryCancelledError, QueryDeadlineError)):
        return FailureClass.CANCELLED
    if isinstance(exc, StageTimeoutError):
        return FailureClass.TIMEOUT
    if isinstance(exc, FaultInjected):
        if exc.fault == "resource_exhausted":
            return FailureClass.OOM
        if exc.fault in ("unavailable", "deadline"):
            return FailureClass.TRANSIENT
        return FailureClass.FATAL
    if isinstance(exc, MemoryError):
        return FailureClass.OOM
    msg = f"{type(exc).__name__}: {exc}"
    if any(t in msg for t in _OOM_TOKENS):
        return FailureClass.OOM
    if any(t in msg for t in _TRANSIENT_TOKENS):
        return FailureClass.TRANSIENT
    return FailureClass.FATAL


def is_mesh_failure(exc: BaseException) -> bool:
    """True when the failure points at the mesh/collective path (or a
    synthetic fault at the `mesh` / `mesh_checkpoint` / `decommission`
    sites — mesh_checkpoint models a host lost mid-stream at a
    snapshot point, decommission a drain that died at its boundary):
    the candidate set for the elastic recovery ladder (gang restart ->
    single-device fallback)."""
    from ..testing.faults import FaultInjected
    if isinstance(exc, FaultInjected):
        return exc.site in ("mesh", "mesh_checkpoint", "decommission")
    msg = f"{type(exc).__name__}: {exc}"
    return any(t in msg for t in _MESH_TOKENS)


class RetryPolicy:
    """One retry budget per query execution, shared by every failure
    class that retries (TRANSIENT and TIMEOUT): exponential backoff with
    jitter, the unified replacement for the ad-hoc fixed-count transient
    loop (spark.task.maxFailures seat).

    delay_n = backoff_ms * 2^n * uniform(0.5, 1.0)

    The default sleep is the INTERRUPTIBLE lifecycle wait
    (execution/lifecycle.py): a backoff wakes immediately when the
    query is cancelled and is capped by the remaining queryDeadlineMs
    budget — raising the structured lifecycle error instead of
    sleeping into a dead query. Pass an explicit `sleep` to opt out
    (tests that count slept milliseconds do).
    """

    def __init__(self, max_retries: int, backoff_ms: float,
                 sleep=None, rng: Optional[random.Random] = None):
        self.max_retries = max(0, int(max_retries))
        self.remaining = self.max_retries
        self.backoff_ms = max(0.0, float(backoff_ms))
        self.attempts = 0
        self.total_sleep_ms = 0.0
        self._sleep = sleep
        self._rng = rng or random.Random()

    def attempt_retry(self) -> Optional[float]:
        """Consume one retry and sleep the backoff. Returns the slept
        milliseconds, or None when the budget is exhausted (caller must
        surface the error). Raises the structured lifecycle error when
        the query was cancelled / deadlined — a retry of a dead query
        must not consume budget or sleep."""
        if self.remaining <= 0:
            return None
        from .lifecycle import checkpoint, sleep as _lc_sleep
        # cooperative boundary BEFORE paying the backoff: the chaos
        # matrix's retry-backoff delivery point
        checkpoint("retry_backoff")
        if self._sleep is None:
            self._sleep = _lc_sleep
        delay_ms = self.backoff_ms * (2 ** self.attempts)
        delay_ms *= 0.5 + self._rng.random() * 0.5
        if delay_ms > 0:
            self._sleep(delay_ms / 1e3)
        self.attempts += 1
        self.remaining -= 1
        self.total_sleep_ms += delay_ms
        return delay_ms

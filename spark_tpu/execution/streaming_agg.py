"""Streaming (chunked) aggregation driver.

The reference streams rows through operator iterators so working sets
never materialize (`WholeStageCodegenExec`'s produce/consume loop,
`TungstenAggregationIterator.scala:82`); a naive XLA translation instead
materializes the whole scan in HBM and dies on inputs larger than device
memory. This driver restores the streaming discipline at batch
granularity: a jitted `update(tables, chunk) -> tables` step is compiled
once and driven over input chunks (device-synthesized range chunks, or
host-ingested scan chunks), with accumulator tables donated across steps.
Narrow ops (project/filter) replay inside the update step, so XLA still
fuses scan->filter->aggregate into one kernel per chunk.

Streaming applies when the aggregate takes the dense-domain direct path
(statically-bounded group count). The sort-based general path falls back
to whole-input execution.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column, bucket_capacity
from ..plan import physical as P
from . import aggregate as agg_kernels
from .recovery import CHECKPOINT_EVERY_KEY, ChunkRetrier

CHUNK_ROWS_KEY = "spark_tpu.sql.execution.streamingChunkRows"


def conf_compile_suffix(conf) -> str:
    """Conf values baked into traced programs but absent from plan
    describe() strings. Every compiled-stage cache key (executor stages
    and the chunk drivers below) appends this, so one stage cache
    shared across sessions with different overlays — or one session
    mutating conf between runs — can never serve a program compiled
    under other settings."""
    return (f"#k{conf.get('spark_tpu.sql.aggregate.kernelMode')}"
            f"#d{conf.get('spark_tpu.sql.aggregate.maxDirectDomain')}"
            f"#g{conf.get('spark_tpu.sql.execution.bucketGrowth')}"
            # mesh composition: shard_map closes over the Mesh object,
            # so a decommission that changed the device pool (same n,
            # different devices) must not reuse a program compiled
            # over a mesh containing the drained device
            f"#x{conf.get('spark_tpu.sql.mesh.excludeDevices')}"
            # join kernel choice + table-shape confs are baked into the
            # traced probe/build programs (execution/hash_join.py)
            f"#j{conf.get('spark_tpu.sql.join.kernelMode')}"
            f"#jl{conf.get('spark_tpu.sql.join.hashLoadFactor')}"
            f"#jp{conf.get('spark_tpu.sql.join.hashMaxProbe')}"
            f"#js{conf.get('spark_tpu.sql.join.hashMaxTableSlots')}"
            f"#jm{conf.get('spark_tpu.sql.join.hashMinProbeRows')}"
            f"#jr{conf.get('spark_tpu.sql.join.hashProbeBuildRatio')}")


#: join types where per-probe-chunk execution is sound: each probe row's
#: output is independent of other probe rows (right/full append
#: build-side rows once globally, so chunking the probe would emit them
#: per chunk)
_CHUNKABLE_JOINS = ("inner", "left", "left_semi", "left_anti")


def find_streamable_chain(agg: "P.HashAggregateExec",
                          allow_joins: bool = True
                          ) -> Optional[Tuple[List, P.LeafExec]]:
    """agg.child must be a chain of Project/Filter — and, when
    `allow_joins`, probe-side-chunkable joins (the build side is an
    independent subtree, materialized once) — over a single leaf."""
    chain = []
    node = agg.child
    while True:
        if isinstance(node, (P.ProjectExec, P.FilterExec)):
            chain.append(node)
            node = node.children[0]
        elif isinstance(node, P.RuntimeFilterExec):
            # a runtime filter is a pure pruning optimization: the join
            # it guards re-checks every key, so the streamed replay can
            # drop it (chunking already bounds residency)
            node = node.children[0]
        elif allow_joins and isinstance(node, P.JoinExec) \
                and node.how in _CHUNKABLE_JOINS:
            chain.append(node)
            node = node.children[0]  # continue down the probe side
        else:
            break
    if isinstance(node, (P.RangeExec, P.ScanExec)):
        return chain, node
    return None


def _replay_chain(chain: List, ctx, batch: Batch,
                  builds: Optional[dict] = None) -> Batch:
    for op in reversed(chain):
        if isinstance(op, P.JoinExec):
            batch = op.compute(ctx, [batch, builds[op.tag]])
        else:
            batch = op.compute(ctx, [batch])
    return batch


def apply_join_overflow(flags, metrics, joins) -> bool:
    """Parse one chunk update's `join_overflow_`/`join_nonunique_`/
    `join_hashsat_` flag families and apply capacity growth /
    unique-build / hash-kernel fallbacks to `joins`. Returns True when
    anything changed — the caller must re-jit and retry the SAME chunk
    against the pre-update state. The ONE copy of the chunked-join AQE
    protocol, shared by every chunk driver (direct stream, partial
    spill, external collect)."""
    overflow = [k for k, v in flags.items()
                if k.startswith(("join_overflow_", "join_nonunique_",
                                 "join_hashsat_"))
                and bool(v)]
    if not overflow:
        return False
    for k in overflow:
        if k.startswith("join_nonunique_"):
            tag = k[len("join_nonunique_"):]
            for j in joins:
                if j.tag == tag:
                    j.unique_build = False
            continue
        if k.startswith("join_hashsat_"):
            tag = k[len("join_hashsat_"):]
            for j in joins:
                if j.tag == tag:
                    j.hash_fallback = False
            continue
        tag = k[len("join_overflow_"):]
        total = int(metrics[f"join_rows_{tag}"])
        for j in joins:
            if j.tag == tag:
                j.out_cap = bucket_capacity(max(total, 8))
    return True


def prepare_chunk_joins(chain: List, conf, first_cap: int, recovery=None):
    """Shared chunk-driver setup: materialize each probe-side join's
    build subtree once (QueryStageExec role) and seed missing output
    capacities with the CHUNK capacity. Returns (joins, builds,
    saved_caps); learned caps stay on the plan nodes afterwards so the
    AQE cap harvest persists them — callers restore `saved_caps` only
    when aborting before any chunk ran."""
    joins = [op for op in chain if isinstance(op, P.JoinExec)]
    builds = {j.tag: _materialize_subtree(j.children[1], conf, recovery)
              for j in joins}
    saved_caps = {j.tag: j.out_cap for j in joins}
    for j in joins:
        if j.out_cap is None:
            j.out_cap = first_cap
    return joins, builds, saved_caps


def _materialize_subtree(root: P.PhysicalPlan, conf, recovery=None) -> Batch:
    """Compile + run an independent subtree (a join's build side) with
    its own AQE capacity-retry loop — a stage materialization, like the
    reference's QueryStageExec. Completed materializations land in the
    recovery stage-output memo (the surviving-shuffle-file analog), so
    a downstream failure's re-execution replays them instead of
    re-running."""
    if recovery is not None:
        hit = recovery.memo_get(("build", id(root)),
                                label=root.simple_string())
        if hit is not None:
            return hit
    scans: List[P.LeafExec] = []

    def collect(n):
        if getattr(n, "needs_input", False):
            scans.append(n)
        for c in n.children:
            collect(c)

    collect(root)
    from ..io.device_cache import load_scan
    inputs = [load_scan(s, conf) if isinstance(s, P.ScanExec) else s.load()
              for s in scans]
    # the executor's capacity setters, so every overflow family the main
    # AQE loop knows (join/exchange/aggregate) retries here too
    from .executor import QueryExecution
    adaptive = bool(conf.get("spark_tpu.sql.adaptive.enabled"))

    for _attempt in range(8):
        def run(ins):
            ctx = P.ExecContext(conf)
            counter = [0]

            def replay(n):
                if getattr(n, "needs_input", False):
                    b = ins[counter[0]]
                    counter[0] += 1
                    return b
                return n.compute(ctx, [replay(c) for c in n.children])

            out = replay(root)
            return out, ctx.flags, ctx.metrics

        batch, flags, metrics = jax.jit(run)(inputs)
        flags, metrics = jax.device_get((flags, metrics))
        overflow = [k for k, v in flags.items()
                    if k.startswith(("join_overflow_", "join_nonunique_",
                                     "join_hashsat_",
                                     "exch_overflow_", "agg_overflow_"))
                    and bool(v)]
        if not overflow:
            if recovery is not None:
                recovery.memo_put(("build", id(root)), batch)
            return batch
        if not adaptive and any(
                not k.startswith(("join_nonunique_", "join_hashsat_"))
                for k in overflow):
            raise RuntimeError(
                f"build-side capacity overflow in {overflow} with "
                f"adaptive re-planning disabled")
        for k in overflow:
            if k.startswith("join_nonunique_"):
                QueryExecution._set_join_nonunique(
                    root, k[len("join_nonunique_"):])
            elif k.startswith("join_hashsat_"):
                QueryExecution._set_join_hash_fallback(
                    root, k[len("join_hashsat_"):])
            elif k.startswith("join_overflow_"):
                tag = k[len("join_overflow_"):]
                total = int(metrics[f"join_rows_{tag}"])
                QueryExecution._set_join_cap(
                    root, tag, bucket_capacity(max(total, 8)))
            elif k.startswith("exch_overflow_"):
                tag = k[len("exch_overflow_"):]
                mx = int(metrics[f"exch_max_{tag}"])
                QueryExecution._set_exchange_cap(
                    root, tag, bucket_capacity(max(mx, 8)))
            else:
                tag = k[len("agg_overflow_"):]
                total = int(metrics[f"agg_groups_{tag}"])
                QueryExecution._set_agg_groups(root, tag, max(total, 8))
    raise RuntimeError("build-side capacity did not converge")


def _range_chunk(leaf: P.RangeExec, start, chunk_rows: int,
                 rows_total: int) -> Batch:
    """Synthesize one chunk of a Range in-trace; `start` is a traced row
    offset so one compiled step serves every chunk."""
    ids = leaf.start + leaf.step * (start + jnp.arange(chunk_rows,
                                                      dtype=jnp.int64))
    sel = (start + jnp.arange(chunk_rows, dtype=jnp.int64)) < rows_total
    return Batch({"id": Column(ids, T.LONG, bits=leaf._id_bits())}, sel)


def stream_range_aggregate(agg: "P.HashAggregateExec", chain: List,
                           leaf: P.RangeExec, conf,
                           cache: Optional[dict] = None) -> Optional[Batch]:
    """Run agg over a big Range in chunks. Returns the result batch, or
    None when the direct path doesn't apply. `cache` (the session stage
    cache) persists the compiled update step across executions — the
    analog of the reference's Janino codegen cache."""
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    rows_total = leaf.num_rows()

    key = (f"stream_range:{agg.describe()}:{chunk_rows}:{rows_total}"
           + conf_compile_suffix(conf))
    run = cache.get(key) if cache is not None else None
    if run is None:
        ctx = P.ExecContext(conf)
        probe = _replay_chain(chain, ctx,
                              _range_chunk(leaf, jnp.int64(0), 8, rows_total))
        prep = agg.prepare_direct(probe, conf)
        if prep is None:
            return None
        n_chunks = -(-rows_total // chunk_rows)

        # the source is device-synthesized, so the whole chunk loop fuses
        # into ONE dispatch (a lax.fori_loop with carried tables) — no
        # host round-trip per chunk
        if any(a.func.uses_row_base for a in agg.agg_exprs) \
                and n_chunks * chunk_rows >= (1 << 30):
            raise RuntimeError(
                "first/last over a streamed range exceeds the 2^30 "
                f"packed-position bound ({rows_total} rows)")

        @jax.jit
        def run():
            def body(i, tables):
                ctx = P.ExecContext(conf)
                b = _replay_chain(
                    chain, ctx,
                    _range_chunk(leaf, i.astype(jnp.int64) * chunk_rows,
                                 chunk_rows, rows_total))
                return agg.direct_update_tables(
                    tables, b, prep, conf,
                    row_base=i.astype(jnp.int64) * chunk_rows)

            tables = jax.lax.fori_loop(0, n_chunks, body,
                                       agg.direct_init_tables(prep))
            return agg.direct_finalize_tables(tables, prep)

        if cache is not None:
            cache[key] = run
    return run()


def stream_scan_aggregate(agg: "P.HashAggregateExec", chain: List,
                          leaf: P.ScanExec, conf,
                          cache: Optional[dict] = None,
                          recovery=None) -> Optional[Batch]:
    """Run agg over a chunked Scan: host ingests record-batch chunks
    (uniform bucketed capacity so the update step compiles once) while the
    device reduces — the double-buffered host->HBM pipeline of SURVEY.md
    section 2.5 'Async/overlap' (io/sources.py PrefetchChunkIterator
    decodes chunk N+1 on a background thread while chunk N computes)."""
    from ..io.sources import maybe_prefetch
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    chunks = maybe_prefetch(
        leaf.source.load_chunks(leaf.required_columns,
                                leaf.pushed_filters, chunk_rows),
        conf, recovery)
    try:
        return _stream_scan_aggregate_inner(agg, chain, conf, cache,
                                            recovery, chunks,
                                            chunk_rows)
    finally:
        # deterministic worker shutdown on EVERY exit — normal
        # exhaustion, fallback `return None`, or an exception (fault,
        # cancellation) unwinding mid-stream: no prefetch daemon may
        # outlive its query (lockwatch assert_no_thread_leak)
        if hasattr(chunks, "close"):
            chunks.close()


def _stream_scan_aggregate_inner(agg, chain, conf, cache, recovery,
                                 chunks, chunk_rows):
    first = next(iter(chunks), None)
    if first is None:
        return None

    joins, builds, saved_caps = prepare_chunk_joins(
        chain, conf, first.capacity, recovery)

    def make_update():
        key = (f"stream_scan:{agg.describe()}:{chunk_rows}"
               + conf_compile_suffix(conf))
        bundle = cache.get(key) if cache is not None else None
        if bundle is None:
            ctx = P.ExecContext(conf)
            probe = _replay_chain(chain, ctx, first, builds)
            prep0 = agg.prepare_direct(probe, conf)
            if prep0 is None:
                return None

            if joins:
                def update(tables, b, bb, row_base):
                    ctx = P.ExecContext(conf)
                    b = _replay_chain(chain, ctx, b, bb)
                    new = agg.direct_update_tables(tables, b, prep0, conf,
                                                   row_base=row_base)
                    return new, ctx.flags, ctx.metrics

                # no donation: a join-capacity overflow must re-run the
                # SAME chunk against the pre-update tables
                bundle = (prep0, jax.jit(update))
            else:
                def update(tables, b, row_base):
                    ctx = P.ExecContext(conf)
                    b = _replay_chain(chain, ctx, b)
                    return agg.direct_update_tables(tables, b, prep0, conf,
                                                    row_base=row_base)

                # join-free hot path: donate tables, no per-chunk host
                # sync — the double-buffered host->HBM overlap
                bundle = (prep0, jax.jit(update, donate_argnums=(0,)))
            if cache is not None:
                cache[key] = bundle
        return bundle

    bundle = make_update()
    if bundle is None:
        for j in joins:  # leave the whole-input fallback's caps alone
            j.out_cap = saved_caps[j.tag]
        return None
    prep, update_fn = bundle

    check_dicts = _dict_growth_guard(agg, prep)
    tables = agg.direct_init_tables(prep)

    # running row base for position-packed aggregates: each chunk's
    # stride covers the largest post-replay capacity (join out_caps only
    # grow, so bases stay collision-free even across mid-run re-jits)
    row_base = 0

    def chunk_stride(b):
        return max([b.capacity] + [j.out_cap or 0 for j in joins])

    def check_bound(b):
        if row_base + chunk_stride(b) >= (1 << 30) and \
                any(a.func.uses_row_base for a in agg.agg_exprs):
            raise RuntimeError(
                "first/last over a streamed scan exceeds the 2^30 "
                "packed-position bound")

    def run_chunk(tables, b):
        nonlocal update_fn
        check_bound(b)
        base = jnp.asarray(row_base, jnp.int64)
        if not joins:
            return update_fn(tables, b, base)
        for _attempt in range(8):
            new, flags, metrics = update_fn(tables, b, builds, base)
            flags, metrics = jax.device_get((flags, metrics))
            if not apply_join_overflow(flags, metrics, joins):
                return new
            # out_cap is part of describe(): re-jit under the new key,
            # then retry the SAME chunk against the pre-update tables
            # (the grown out_cap widens the position stride — re-check)
            _prep2, update_fn = make_update()
            check_bound(b)
        raise RuntimeError("streamed join capacity did not converge")

    # chunk-granular retry (execution/recovery.py): carry state only
    # advances after a chunk succeeds, so a TRANSIENT fault replays
    # exactly the failed chunk against the pre-chunk tables
    retrier = ChunkRetrier(conf, recovery)
    ci = 0
    b = first
    while b is not None:
        check_dicts(b)
        tables = retrier.run(lambda bb=b: run_chunk(tables, bb), chunk=ci)
        row_base += chunk_stride(b)
        ci += 1
        b = next(chunks, None)  # ingest un-retried: see ChunkRetrier

    dict_overrides = dict(chunks.dictionaries) if hasattr(
        chunks, "dictionaries") else {}
    return agg.direct_finalize_tables(tables, prep, dict_overrides or None)


def stream_scan_aggregate_spill(agg: "P.HashAggregateExec", chain: List,
                                leaf: P.ScanExec, conf,
                                cache: Optional[dict] = None,
                                recovery=None, skip_chunks: int = 0,
                                seed_partials: Optional[List] = None):
    """Out-of-core aggregation for UNBOUNDED group keys (no static
    domain — e.g. TPC-H Q3's l_orderkey): stream probe chunks through
    device-resident build sides, reduce each chunk with a PARTIAL-mode
    sort aggregate (num_segments = chunk capacity, so per-chunk overflow
    is impossible), and spill the compacted partial batches to host
    Arrow buffers — host RAM plays the role the reference's executor
    disk plays for `UnsafeExternalSorter.java:1` /
    `ExternalAppendOnlyMap.scala:55`. Returns (concatenated host partial
    table, partial node) for the caller to re-reduce with a FINAL
    aggregate; None when the shape doesn't apply.

    The checkpoint-restore path reuses this driver to RESUME a failed
    mesh stream single-device: `skip_chunks` advances the chunk cursor
    past what the checkpoint already covers, and `seed_partials`
    prepends the checkpointed partial tables to the spill list."""
    from ..io.sources import maybe_prefetch

    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    chunks = maybe_prefetch(
        leaf.source.load_chunks(leaf.required_columns,
                                leaf.pushed_filters, chunk_rows),
        conf, recovery)
    try:
        return _stream_scan_aggregate_spill_inner(
            agg, chain, conf, cache, recovery, skip_chunks,
            seed_partials, chunks, chunk_rows)
    finally:
        # join the prefetch worker on every exit (see
        # stream_scan_aggregate): a cancelled/deadlined query must not
        # leak its ingest daemon
        if hasattr(chunks, "close"):
            chunks.close()


def _stream_scan_aggregate_spill_inner(agg, chain, conf, cache, recovery,
                                       skip_chunks, seed_partials,
                                       chunks, chunk_rows):
    import copy
    import pyarrow as pa
    if skip_chunks:
        if not hasattr(chunks, "skip_chunks") or \
                chunks.skip_chunks(skip_chunks) < skip_chunks:
            return None  # stream shorter than the checkpoint cursor
    first = next(iter(chunks), None)

    partial = copy.copy(agg)
    partial.mode = "partial"
    # num_segments falls back to the post-replay batch capacity: a chunk
    # can never have more groups than rows, so the per-chunk partial
    # needs no overflow retry of its own
    partial.est_groups = None

    if first is None:
        if seed_partials:
            # resume landed exactly at end-of-stream: the checkpoint
            # already covers every chunk
            return pa.concat_tables(list(seed_partials),
                                    promote_options="permissive"), partial
        return None

    joins, builds, saved_caps = prepare_chunk_joins(
        chain, conf, first.capacity, recovery)

    def make_update():
        key = (f"stream_spill:{agg.describe()}:{chunk_rows}"
               + conf_compile_suffix(conf))
        fn = cache.get(key) if cache is not None else None
        if fn is None:
            def update(b, bb):
                ctx = P.ExecContext(conf)
                b = _replay_chain(chain, ctx, b, bb)
                out = partial.compute(ctx, [b])
                return out, ctx.flags, ctx.metrics

            fn = jax.jit(update)
            if cache is not None:
                cache[key] = fn
        return fn

    update_fn = make_update()

    def run_chunk(b):
        nonlocal update_fn
        for _attempt in range(8):
            out, flags, metrics = update_fn(b, builds)
            flags, metrics = jax.device_get((flags, metrics))
            if not apply_join_overflow(flags, metrics, joins):
                return out
            # describe() changed with the grown caps: re-jit and retry
            # the SAME chunk (partials for it were not yet spilled)
            update_fn = make_update()
        raise RuntimeError("spilled join capacity did not converge")

    # spill each chunk's compacted partial to host; dictionary-encoded
    # group keys decode to strings here, so per-chunk dictionaries unify
    # value-wise in the concat (no shared-encoding requirement). The
    # host pull rides inside the retried step: a flake during to_arrow
    # replays only this chunk (its partial was not yet spilled).
    retrier = ChunkRetrier(conf, recovery)
    spilled: List = list(seed_partials or [])
    ci = int(skip_chunks)
    b = first
    while b is not None:
        spilled.append(retrier.run(
            lambda bb=b: run_chunk(bb).to_arrow(), chunk=ci))
        ci += 1
        b = next(chunks, None)  # ingest un-retried: see ChunkRetrier
    for j in joins:
        j.out_cap = saved_caps[j.tag] if saved_caps[j.tag] is not None \
            else j.out_cap
    table = pa.concat_tables(spilled, promote_options="permissive")
    return table, partial


def try_stream_aggregate_spill(agg: "P.HashAggregateExec", conf,
                               cache: Optional[dict] = None,
                               recovery=None):
    """Device-budget gate for the out-of-core partial-spill path:
    engages when the probe scan's working set cannot stay resident —
    its estimated footprint exceeds the per-query
    `spark_tpu.sql.memory.deviceBudget`, or the cross-query arbiter
    (service/arbiter.py) denied the residency lease from the shared
    HBM pool (UnifiedMemoryManager.scala:49's execution-pool analog,
    now genuinely shared across concurrent queries)."""
    from ..service.arbiter import admit_scan_resident, out_of_core_active
    if not out_of_core_active(conf) or agg.mode != "complete":
        return None
    if any(a.func.uses_row_base for a in agg.agg_exprs):
        return None  # packed-position aggs need whole-input row order
    if any(getattr(a.func, "positional", False) for a in agg.agg_exprs):
        return None  # no accumulator decomposition: whole-input only
    found = find_streamable_chain(agg)
    if found is None:
        return None
    chain, leaf = found
    if not isinstance(leaf, P.ScanExec) or \
            not hasattr(leaf.source, "load_chunks"):
        return None
    if admit_scan_resident(conf, leaf):
        return None
    return stream_scan_aggregate_spill(agg, chain, leaf, conf, cache,
                                       recovery)


def _dict_growth_guard(agg: "P.HashAggregateExec", prep):
    """Guard: a chunk whose dictionary outgrows the padded direct domain
    would silently alias groups; fail loudly instead (shared by the
    single-chip and mesh streaming drivers)."""
    dict_limits = {}
    for g, (dom, _lo), dic in zip(agg.group_exprs, prep.domains,
                                  prep.key_dicts):
        if dic is not None and len(g.references()) == 1:
            dict_limits[next(iter(g.references()))] = dom

    def check_dicts(b: Batch):
        for name, limit in dict_limits.items():
            col = b.columns.get(name)
            if col is not None and col.dictionary is not None \
                    and len(col.dictionary) > limit:
                raise RuntimeError(
                    f"dictionary of {name!r} grew past the padded direct "
                    f"domain ({len(col.dictionary)} > {limit}); raise "
                    f"spark_tpu.sql.aggregate.maxDirectDomain or disable "
                    f"streaming")

    return check_dicts


def checkpoint_key(agg: "P.HashAggregateExec", leaf: P.ScanExec,
                   chunk_rows: int) -> str:
    """Plan-independent identity of a resumable stream: the mesh
    partial aggregate that SAVES a checkpoint and the single-device
    complete aggregate that RESTORES it are different physical nodes
    from different plans, but stream the same source rows under the
    same chunk boundaries into the same aggregation. Source identity
    (cache token), pruned columns, pushed-filter count, group/agg
    names and the chunk size pin all of that; any mismatch (e.g. the
    OOM ladder shrank streamingChunkRows) makes the checkpoint
    unmatchable and the fallback safely restarts from chunk 0."""
    token = leaf.source.cache_token()
    src = repr(token) if token is not None else f"name:{leaf.source.name}"
    cols = sorted(leaf.required_columns or [])
    # filter VALUES, not count: two same-shaped aggregates over the
    # same source differing only in predicate literals must not share
    # a checkpoint slot (name() renders literals: "(l_shipdate <= N)")
    filters = sorted(f.name() for f in (leaf.pushed_filters or ()))
    groups = [g.name() for g in agg.group_exprs]
    aggs = [f"{type(a.func).__name__}:{a.out_name}" for a in agg.agg_exprs]
    return (f"{src}|cols{cols}|f{filters}"
            f"|g{groups}|a{aggs}|c{chunk_rows}")


def _with_dict_overrides(batch: Batch, dict_overrides: dict) -> Batch:
    """Swap grown global dictionaries into a partial/final batch's
    dictionary-encoded columns (codes handed out earlier stay valid —
    DictUnifier grows append-only)."""
    if not dict_overrides:
        return batch
    cols = dict(batch.columns)
    for name, dic in dict_overrides.items():
        if name in cols and cols[name].dictionary is not None:
            c = cols[name]
            cols[name] = type(c)(c.data, c.dtype, c.validity, dic)
    return Batch(cols, batch.selection)


def resume_from_mesh_checkpoint(agg: "P.HashAggregateExec", conf,
                                cache: Optional[dict] = None,
                                recovery=None):
    """Mesh-fallback restore: when the failed mesh stream left a
    checkpoint matching this (single-device, complete-mode) aggregate,
    resume at the checkpointed chunk cursor — stream the REMAINING
    chunks through the partial-spill driver with the checkpointed
    partial rows prepended, for the caller to re-reduce with a FINAL
    aggregate. Returns (partial table, partial node) like
    stream_scan_aggregate_spill, or None when no checkpoint applies."""
    if recovery is None or not recovery.checkpoints:
        return None
    if agg.mode != "complete":
        return None
    if any(a.func.uses_row_base for a in agg.agg_exprs):
        return None  # never checkpointed (position packing is per-run)
    if any(getattr(a.func, "positional", False) for a in agg.agg_exprs):
        return None
    found = find_streamable_chain(agg)
    if found is None:
        return None
    chain, leaf = found
    if not isinstance(leaf, P.ScanExec) or \
            not hasattr(leaf.source, "load_chunks"):
        return None
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    ck = recovery.get_checkpoint(checkpoint_key(agg, leaf, chunk_rows))
    if ck is None:
        return None
    out = stream_scan_aggregate_spill(agg, chain, leaf, conf, cache,
                                      recovery=recovery,
                                      skip_chunks=ck.cursor,
                                      seed_partials=[ck.table])
    if out is None:
        return None
    replayed = recovery.restore_replayed(ck.key, ck.cursor)
    recovery.record("checkpoint_restore", None, cursor=int(ck.cursor),
                    ckpt_rows=int(ck.table.num_rows),
                    chunks_replayed=replayed)
    return out


def _streamable_string_keys(agg, child_schema) -> bool:
    """Only bare string column references stream (their dictionary grows
    append-only via DictUnifier); derived string keys rebuild per-chunk
    dictionaries with unstable codes."""
    from ..expr import Alias, ColumnRef
    for g in agg.group_exprs:
        e = g
        while isinstance(e, Alias):
            e = e.child
        if not isinstance(e, ColumnRef) and \
                isinstance(e.dtype(child_schema), T.StringType):
            return False
    return True


def stream_scan_aggregate_mesh(agg: "P.HashAggregateExec", mesh, conf,
                               cache: Optional[dict] = None,
                               recovery=None) -> Optional[Batch]:
    """Chunked host ingest under a mesh: each chunk is sharded over the
    data axis and folded into PER-SHARD accumulator tables by a jitted
    shard_map step; the final step emits each shard's partial batch, so
    the (already planned) exchange + final aggregate run unchanged.

    This is the round-2 gap VERDICT weak #7: distributed runs used to
    materialize entire scans. The partial tables are [n, total]-shaped
    arrays sharded on dim 0 — only accumulator-table bytes stay resident
    between chunks."""
    if agg.mode != "partial":
        return None
    if any(getattr(a.func, "positional", False) for a in agg.agg_exprs):
        return None  # no accumulator decomposition: whole-input only
    # mesh streaming is unary-only: a streamed join would need the build
    # replicated per shard — future work
    found = find_streamable_chain(agg, allow_joins=False)
    if found is None:
        return None
    chain, leaf = found
    if not isinstance(leaf, P.ScanExec):
        return None  # Range synthesizes in-trace; nothing to stream
    if not _streamable_string_keys(agg, agg.child.schema()):
        return None
    if not hasattr(leaf.source, "load_chunks"):
        return None
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    est = leaf.source.estimated_rows()
    if est is not None and est <= chunk_rows:
        return None
    if _prefer_resident(leaf, conf):
        return None

    from ..io.sources import maybe_prefetch
    from ..observability.spans import current_shard_telemetry
    n = int(mesh.devices.size)
    telem = current_shard_telemetry()
    needs_base = any(a.func.uses_row_base for a in agg.agg_exprs)
    every = int(conf.get(CHECKPOINT_EVERY_KEY))
    # position-packed aggregates are excluded from checkpoint/resume
    # AND rebalance — their packed row bases encode assignment order
    ck_key = checkpoint_key(agg, leaf, chunk_rows) \
        if recovery is not None and not needs_base else None
    save_key = ck_key if every > 0 else None
    # elastic resume: a gang restart (or decommission re-execution)
    # re-enters this driver with the failed stream's checkpoint intact
    # — skip the covered chunks and merge the checkpointed partial
    # rows at emit, so the recovery replays at most everyChunks chunks
    # ON the mesh (the mesh-side analog of resume_from_mesh_checkpoint)
    ck = recovery.get_checkpoint(ck_key) if ck_key is not None else None
    chunks = maybe_prefetch(
        leaf.source.load_chunks(leaf.required_columns,
                                leaf.pushed_filters, chunk_rows),
        conf, recovery)
    try:
        return _stream_scan_aggregate_mesh_inner(
            agg, chain, mesh, conf, cache, recovery, chunks,
            chunk_rows, n, telem, needs_base, every, ck_key,
            save_key, ck)
    finally:
        # join the prefetch worker on every exit (see
        # stream_scan_aggregate): a mesh fault or a cancellation
        # unwinding mid-stream must not leak its ingest daemon
        if hasattr(chunks, "close"):
            chunks.close()


def _stream_scan_aggregate_mesh_inner(agg, chain, mesh, conf, cache,
                                      recovery, chunks, chunk_rows, n,
                                      telem, needs_base, every, ck_key,
                                      save_key, ck):
    import jax
    from jax.sharding import PartitionSpec as Psp
    from ..parallel.mesh import shard_map
    from ..parallel.mesh import AXIS
    from ..parallel import elastic as EL
    import pyarrow as pa
    import time as _time
    if ck is not None:
        if not hasattr(chunks, "skip_chunks") or \
                chunks.skip_chunks(ck.cursor) < ck.cursor:
            return None  # stream shorter than the cursor: unmatchable

    def record_restore():
        replayed = recovery.restore_replayed(ck_key, ck.cursor)
        recovery.record("checkpoint_restore", None,
                        cursor=int(ck.cursor),
                        ckpt_rows=int(ck.table.num_rows),
                        chunks_replayed=replayed, driver="mesh")

    t_in0 = _time.perf_counter()
    first = next(iter(chunks), None)
    t_in1 = _time.perf_counter()
    if first is None:
        if ck is not None:
            # resume landed exactly at end-of-stream: the checkpoint
            # already covers every chunk — its partial rows ARE the
            # stream's result (the exchange + final above re-reduce)
            record_restore()
            return Batch.from_arrow(ck.table)
        return None
    key = (f"stream_mesh:{agg.describe()}:{chunk_rows}:{n}"
           + conf_compile_suffix(conf))
    bundle = cache.get(key) if cache is not None else None
    if bundle is None:
        ctx = P.ExecContext(conf)
        probe = _replay_chain(chain, ctx, first)
        prep = agg.prepare_direct(probe, conf)
        if prep is None:
            return None

        def update(tables, b, chunk_base):
            t = jax.tree_util.tree_map(lambda x: x[0], tables)
            ctx = P.ExecContext(conf)
            local = _replay_chain(chain, ctx, b)
            # unique packed positions: chunks stride the full chunk
            # capacity (host counter), shards stride the local capacity
            base = chunk_base + jax.lax.axis_index(AXIS) \
                .astype(jnp.int64) * local.capacity
            new = agg.direct_update_tables(t, local, prep, conf,
                                           row_base=base)
            # per-shard telemetry channel: this shard's live rows this
            # chunk, shape [1] so the sharded stack is [n] with one
            # device-resident slot per shard (spans.ShardStreamTelemetry
            # times per-shard readiness off exactly this array)
            live = jnp.sum(local.selection_mask().astype(jnp.int64))[None]
            return jax.tree_util.tree_map(lambda x: x[None], new), live

        def emit(tables):
            t = jax.tree_util.tree_map(lambda x: x[0], tables)
            return agg.direct_partial_batch(t, prep)

        update_step = jax.jit(shard_map(
            update, mesh=mesh, in_specs=(Psp(AXIS), Psp(AXIS), Psp()),
            out_specs=(Psp(AXIS), Psp(AXIS)), check_vma=False),
            donate_argnums=(0,))
        emit_step = jax.jit(shard_map(
            emit, mesh=mesh, in_specs=(Psp(AXIS),),
            out_specs=Psp(AXIS), check_vma=False))
        # prep MUST live in the bundle: the jitted closures capture it,
        # so a cache hit with a fresh prep would silently mix layouts
        bundle = (prep, update_step, emit_step)
        if cache is not None:
            cache[key] = bundle
    prep, update_step, emit_step = bundle

    # per-shard neutral tables, [n, total] sharded on dim 0
    cnt0, accs0 = agg.direct_init_tables(prep)
    tables = (jnp.broadcast_to(cnt0, (n,) + cnt0.shape),
              [[jnp.broadcast_to(a, (n,) + a.shape) for a in row]
               for row in accs0])

    check_dicts = _dict_growth_guard(agg, prep)
    chunk_base = 0

    def row_width(b):
        return sum(c.data.dtype.itemsize
                   + (1 if c.validity is not None else 0)
                   for c in b.columns.values())

    # straggler rebalancing (parallel/elastic.py): inert until the
    # ElasticRebalancer flags a shard via on_straggler, then each
    # chunk's rows skew away from it. Position-packed aggregates keep
    # the even split (their packed bases encode assignment).
    rebal = EL.RebalanceState(n, conf, recovery=recovery) \
        if not needs_base else None

    def step(tables, b, ci):
        nonlocal chunk_base
        padded = EL.pad_chunk_for_shards(b, n, rebal)
        if needs_base and chunk_base + padded.capacity >= (1 << 30):
            raise RuntimeError(
                "first/last over a streamed mesh scan exceeds the 2^30 "
                "packed-position bound")
        t_disp = _time.perf_counter()
        out, shard_rows = update_step(tables, padded,
                                      jnp.asarray(chunk_base, jnp.int64))
        if telem is not None:
            # hot path stays sync-free: the device array is buffered;
            # the PREVIOUS chunk's buffer flushes inside this call
            telem.chunk_dispatched(ci, shard_rows, row_width(b), t_disp)
        chunk_base += padded.capacity
        return out

    def current_dicts() -> dict:
        return dict(chunks.dictionaries) if hasattr(
            chunks, "dictionaries") else {}

    def snapshot():
        # device->host checkpoint of the accumulator state: emit the
        # per-shard partial rows (the exact shape a FINAL aggregate
        # consumes) and decode them against the dictionaries grown so
        # far — every code folded so far is covered (append-only). A
        # RESUMED stream's accumulators only cover the post-cursor
        # chunks: prepend the seed checkpoint so a later restore never
        # loses the head of the stream.
        t = _with_dict_overrides(emit_step(tables),
                                 current_dicts()).to_arrow()
        if ck is not None:
            t = pa.concat_tables([ck.table, t],
                                 promote_options="permissive")
        return t

    # chunk-granular retry + periodic checkpoint (execution/recovery.py)
    if ck is not None:
        # the bundle exists and the cursor was skipped: the resume is
        # definitely running — record it (with its bounded replay)
        record_restore()
    retrier = ChunkRetrier(conf, recovery)
    ci = int(ck.cursor) if ck is not None else 0
    b = first
    with EL.use_rebalance(rebal):
        while b is not None:
            # graceful decommission: a pending drain request applies at
            # the chunk boundary — checkpoint forced at the current
            # cursor so the reduced gang resumes here, then the request
            # surfaces to the executor, which excludes the draining
            # devices and re-executes. The `decommission` seam fires
            # FIRST: a fault injected there models the drain machinery
            # dying, and rides the normal mesh ladder.
            drain, drain_ids = EL.pending_decommission(conf, mesh)
            if drain:
                from ..testing import faults
                faults.fire("decommission")
                if save_key is not None and ci > 0:
                    recovery.save_checkpoint(save_key, ci, snapshot)
                raise EL.MeshDecommissionRequest(drain, drain_ids)
            if telem is not None:
                telem.chunk_ingested(ci, b.capacity,
                                     b.capacity * row_width(b),
                                     t_in0, t_in1)
            check_dicts(b)
            tables = retrier.run(lambda bb=b: step(tables, bb, ci),
                                 chunk=ci)
            ci += 1
            if ck_key is not None:
                # consumed-chunk watermark: bounds the replay a later
                # checkpoint restore reports (restore_replayed)
                recovery.note_progress(ck_key, ci)
            if save_key is not None and ci % every == 0:
                recovery.save_checkpoint(save_key, ci, snapshot)
            t_in0 = _time.perf_counter()
            b = next(chunks, None)  # ingest un-retried: see ChunkRetrier
            t_in1 = _time.perf_counter()

    if telem is not None:
        telem.finish()  # flush the last chunk's buffered records
    out = _with_dict_overrides(emit_step(tables), current_dicts())
    if ck is not None:
        # merge the seed checkpoint's partial rows with the resumed
        # tail's — the FINAL aggregate above re-reduces both
        out = Batch.from_arrow(pa.concat_tables(
            [ck.table, out.to_arrow()], promote_options="permissive"))
    return out


def _prefer_resident(leaf: "P.ScanExec", conf) -> bool:
    """True when the scan should load whole and ride the device-table
    cache instead of streaming: it's already cached, or its estimated
    footprint fits in half the cache budget (so repeated queries skip
    host ingest entirely — the round-3 headline perf fix)."""
    from ..io.device_cache import (CACHE_BYTES_KEY, estimated_scan_bytes,
                                   is_cached, scan_cache_key)
    from ..service.arbiter import admit_scan_resident
    # cheap disqualifiers FIRST: admit_scan_resident takes a
    # full-estimate lease from the shared pool, and leases are held to
    # query end — a scan that was never going to ride the cache must
    # not reserve est-sized headroom while it streams chunk-sized
    budget = int(conf.get(CACHE_BYTES_KEY))
    if budget <= 0:
        return False
    if scan_cache_key(leaf) is None:
        return False  # uncacheable source: residency would re-ingest
    if not is_cached(leaf):
        est_b = estimated_scan_bytes(leaf)
        if est_b is None or est_b > budget // 2:
            return False
    return admit_scan_resident(conf, leaf)
    # False = over the per-query budget, or the shared-pool lease was
    # denied (arbiter): must stream


def try_stream_aggregate(agg: "P.HashAggregateExec", conf,
                         cache: Optional[dict] = None,
                         recovery=None) -> Optional[Batch]:
    if agg.mode != "complete":
        return None
    if any(getattr(a.func, "positional", False) for a in agg.agg_exprs):
        return None  # no accumulator decomposition: whole-input only
    found = find_streamable_chain(agg)
    if found is None:
        return None
    if not _streamable_string_keys(agg, agg.child.schema()):
        return None
    chain, leaf = found
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    if isinstance(leaf, P.RangeExec):
        if any(isinstance(op, P.JoinExec) for op in chain):
            return None  # joined Range: whole-input execution
        if leaf.num_rows() <= chunk_rows:
            return None
        return stream_range_aggregate(agg, chain, leaf, conf, cache)
    est = leaf.source.estimated_rows()
    if est is not None and est <= chunk_rows:
        return None
    if not hasattr(leaf.source, "load_chunks"):
        return None
    if _prefer_resident(leaf, conf):
        return None
    return stream_scan_aggregate(agg, chain, leaf, conf, cache, recovery)

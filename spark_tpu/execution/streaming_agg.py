"""Streaming (chunked) aggregation driver.

The reference streams rows through operator iterators so working sets
never materialize (`WholeStageCodegenExec`'s produce/consume loop,
`TungstenAggregationIterator.scala:82`); a naive XLA translation instead
materializes the whole scan in HBM and dies on inputs larger than device
memory. This driver restores the streaming discipline at batch
granularity: a jitted `update(tables, chunk) -> tables` step is compiled
once and driven over input chunks (device-synthesized range chunks, or
host-ingested scan chunks), with accumulator tables donated across steps.
Narrow ops (project/filter) replay inside the update step, so XLA still
fuses scan->filter->aggregate into one kernel per chunk.

Streaming applies when the aggregate takes the dense-domain direct path
(statically-bounded group count). The sort-based general path falls back
to whole-input execution.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column, bucket_capacity
from ..plan import physical as P
from . import aggregate as agg_kernels

CHUNK_ROWS_KEY = "spark_tpu.sql.execution.streamingChunkRows"


def find_streamable_chain(agg: "P.HashAggregateExec"
                          ) -> Optional[Tuple[List, P.LeafExec]]:
    """agg.child must be a chain of Project/Filter over a single leaf."""
    chain = []
    node = agg.child
    while isinstance(node, (P.ProjectExec, P.FilterExec)):
        chain.append(node)
        node = node.children[0]
    if isinstance(node, (P.RangeExec, P.ScanExec)):
        return chain, node
    return None


def _replay_chain(chain: List, ctx, batch: Batch) -> Batch:
    for op in reversed(chain):
        batch = op.compute(ctx, [batch])
    return batch


def _range_chunk(leaf: P.RangeExec, start, chunk_rows: int,
                 rows_total: int) -> Batch:
    """Synthesize one chunk of a Range in-trace; `start` is a traced row
    offset so one compiled step serves every chunk."""
    ids = leaf.start + leaf.step * (start + jnp.arange(chunk_rows,
                                                      dtype=jnp.int64))
    sel = (start + jnp.arange(chunk_rows, dtype=jnp.int64)) < rows_total
    return Batch({"id": Column(ids, T.LONG)}, sel)


def stream_range_aggregate(agg: "P.HashAggregateExec", chain: List,
                           leaf: P.RangeExec, conf,
                           cache: Optional[dict] = None) -> Optional[Batch]:
    """Run agg over a big Range in chunks. Returns the result batch, or
    None when the direct path doesn't apply. `cache` (the session stage
    cache) persists the compiled update step across executions — the
    analog of the reference's Janino codegen cache."""
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    rows_total = leaf.num_rows()

    key = f"stream_range:{agg.describe()}:{chunk_rows}:{rows_total}"
    run = cache.get(key) if cache is not None else None
    if run is None:
        ctx = P.ExecContext(conf)
        probe = _replay_chain(chain, ctx,
                              _range_chunk(leaf, jnp.int64(0), 8, rows_total))
        prep = agg.prepare_direct(probe, conf)
        if prep is None:
            return None
        n_chunks = -(-rows_total // chunk_rows)

        # the source is device-synthesized, so the whole chunk loop fuses
        # into ONE dispatch (a lax.fori_loop with carried tables) — no
        # host round-trip per chunk
        @jax.jit
        def run():
            def body(i, tables):
                ctx = P.ExecContext(conf)
                b = _replay_chain(
                    chain, ctx,
                    _range_chunk(leaf, i.astype(jnp.int64) * chunk_rows,
                                 chunk_rows, rows_total))
                return agg.direct_update_tables(tables, b, prep)

            tables = jax.lax.fori_loop(0, n_chunks, body,
                                       agg.direct_init_tables(prep))
            return agg.direct_finalize_tables(tables, prep)

        if cache is not None:
            cache[key] = run
    return run()


def stream_scan_aggregate(agg: "P.HashAggregateExec", chain: List,
                          leaf: P.ScanExec, conf,
                          cache: Optional[dict] = None) -> Optional[Batch]:
    """Run agg over a chunked Scan: host ingests record-batch chunks
    (uniform bucketed capacity so the update step compiles once) while the
    device reduces — the double-buffered host->HBM pipeline of SURVEY.md
    section 2.5 'Async/overlap'."""
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    chunks = leaf.source.load_chunks(leaf.required_columns,
                                     leaf.pushed_filters, chunk_rows)
    first = next(iter(chunks), None)
    if first is None:
        return None
    key = f"stream_scan:{agg.describe()}:{chunk_rows}"
    bundle = cache.get(key) if cache is not None else None
    if bundle is None:
        ctx = P.ExecContext(conf)
        probe = _replay_chain(chain, ctx, first)
        prep = agg.prepare_direct(probe, conf)
        if prep is None:
            return None

        def update(tables, b):
            ctx = P.ExecContext(conf)
            b = _replay_chain(chain, ctx, b)
            return agg.direct_update_tables(tables, b, prep)

        bundle = (prep, jax.jit(update, donate_argnums=(0,)))
        if cache is not None:
            cache[key] = bundle
    prep, update_donated = bundle

    # guard: a chunk whose dictionary outgrows the padded domain would
    # silently alias groups; fail loudly instead
    dict_limits = {}
    for g, (dom, _lo), dic in zip(agg.group_exprs, prep.domains,
                                  prep.key_dicts):
        if dic is not None and len(g.references()) == 1:
            dict_limits[next(iter(g.references()))] = dom

    def check_dicts(b: Batch):
        for name, limit in dict_limits.items():
            col = b.columns.get(name)
            if col is not None and col.dictionary is not None \
                    and len(col.dictionary) > limit:
                raise RuntimeError(
                    f"dictionary of {name!r} grew past the padded direct "
                    f"domain ({len(col.dictionary)} > {limit}); raise "
                    f"spark_tpu.sql.aggregate.maxDirectDomain or disable "
                    f"streaming")

    tables = agg.direct_init_tables(prep)
    check_dicts(first)
    tables = update_donated(tables, first)
    for b in chunks:
        check_dicts(b)
        tables = update_donated(tables, b)

    dict_overrides = dict(chunks.dictionaries) if hasattr(
        chunks, "dictionaries") else {}
    return agg.direct_finalize_tables(tables, prep, dict_overrides or None)


def try_stream_aggregate(agg: "P.HashAggregateExec", conf,
                         cache: Optional[dict] = None) -> Optional[Batch]:
    if agg.mode != "complete":
        return None
    found = find_streamable_chain(agg)
    if found is None:
        return None
    # a string group key *derived* from a column (substr, concat, ...)
    # rebuilds its (deduped) dictionary per chunk, so codes are not stable
    # across chunks and the carried tables would mix encodings; only bare
    # column references stream (their dictionary grows append-only via
    # DictUnifier). Derived keys fall back to whole-input execution.
    from ..expr import Alias, ColumnRef
    child_schema = agg.child.schema()
    for g in agg.group_exprs:
        e = g
        while isinstance(e, Alias):
            e = e.child
        if not isinstance(e, ColumnRef) and \
                isinstance(e.dtype(child_schema), T.StringType):
            return None
    chain, leaf = found
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    if isinstance(leaf, P.RangeExec):
        if leaf.num_rows() <= chunk_rows:
            return None
        return stream_range_aggregate(agg, chain, leaf, conf, cache)
    est = leaf.source.estimated_rows()
    if est is not None and est <= chunk_rows:
        return None
    if not hasattr(leaf.source, "load_chunks"):
        return None
    return stream_scan_aggregate(agg, chain, leaf, conf, cache)

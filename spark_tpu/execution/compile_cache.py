"""Persistent cross-process AOT compile cache for stage executables.

XLA compile time is the new Janino compile time (SURVEY §7): Spark's
`CodeGenerator` cache is process-local, and so was ours — the in-memory
`session._stage_cache` dies with its process, so every fresh process
(each bench round, each service restart, each preflight stage) re-paid
the full trace + lower + backend-compile cost for TPC-H/TPC-DS shapes
it had compiled hundreds of times before. This module is the
cross-process seat layered UNDER that cache:

- On an in-memory miss with `spark_tpu.sql.compileCache.enabled` on,
  `executor._compile_stage` compiles the stage through the AOT path
  (``jit(fn).lower(args).compile()``), serializes the executable
  (`jax.experimental.serialize_executable`) and writes it to
  `compileCache.dir` via the shared `state_store.fsync_replace`
  atomic-rename helper — a torn write can never shadow a good entry,
  and concurrent writers (two pooled sessions racing one key) are
  last-write-wins of equivalent bytes.
- On the next process's miss of the same key, the entry deserializes
  (`compile_cache_disk_hits`, a `deserialize` sub-span) instead of
  compiling: a warm serving process never jits a known shape twice.

**Keying.** Entries are named by a digest of the full stage key (plan
describe + compile-relevant conf via `conf_compile_suffix`, exactly the
in-memory key) PLUS an environment fingerprint (jax/jaxlib versions,
backend platform, device kind/count, mesh shape + device ids) PLUS the
call signature (input pytree structure *including aux data* + leaf
shape/dtype). The fingerprint makes a jaxlib upgrade or a drained gang
miss cleanly rather than load a stale executable. The signature guard
matters for correctness, not just shapes: `Column` pytree aux embeds
host DICTIONARIES, so an executable compiled over one dictionary-
encoded table must never serve a batch whose dictionaries differ —
`jax.jit` would retrace on the aux mismatch, and the load path
replicates exactly that discipline by requiring treedef equality
before dispatching a deserialized `Compiled`.

**Faults.** The `compile_cache_load` chaos seam fires inside the
guarded load of an existing entry: ANY failure there (corrupted /
truncated file, unpickle error, backend deserialize rejection, an
injected fault) logs a warning, counts `compile_cache_corrupt`, falls
back to a fresh compile and overwrites the bad entry — a damaged cache
can never fail a query.

**Bounds.** The directory is size-bounded (`compileCache.maxBytes`,
LRU by mtime — loads touch their entry); `manifest.jsonl` records
recently-seen stage keys for the warm-start replay
(`session.warmup()` / `SqlService.start()`), compacted in place.

**Secondary seat.** When the cache is enabled, JAX's native
compilation cache (`jax_compilation_cache_dir`) is pointed at
`<dir>/xla` if the operator hasn't configured it: a fingerprint or
signature miss that still re-lowers an unchanged HLO can then skip the
backend compile even though it re-paid the trace (best-effort;
platform support varies).

Concurrency: `CompileCache._lock` (registered `execution.compile_cache`
in the concurrency registry) serializes writes, eviction and manifest
maintenance within a process; cross-process safety is carried entirely
by the atomic renames + the tolerance of every read path to files
vanishing underneath it.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

ENABLED_KEY = "spark_tpu.sql.compileCache.enabled"
DIR_KEY = "spark_tpu.sql.compileCache.dir"
MAX_BYTES_KEY = "spark_tpu.sql.compileCache.maxBytes"
WARM_START_KEY = "spark_tpu.sql.compileCache.warmStart"

#: entry format version: bumped on any incompatible change to the
#: pickled entry dict, so an old-layout file reads as a clean miss
ENTRY_FORMAT = 1

#: manifest compaction: rewrite once the file passes the byte
#: threshold (an os.stat per append — never a full-file read on the
#: query path), keeping the newest _MANIFEST_MAX_LINES // 2 records
_MANIFEST_MAX_LINES = 4096
_MANIFEST_MAX_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# Keying: environment fingerprint + call signature
# ---------------------------------------------------------------------------


def env_fingerprint(mesh=None) -> Dict:
    """What must match for a serialized executable to be loadable AND
    correct in this process: toolchain versions, backend, device kind
    and pool size — plus, for mesh stages, the exact gang shape and
    device ids (`shard_map` closes over the Mesh; a drained gang or a
    re-numbered pool must miss cleanly, the same reason
    `mesh.excludeDevices` rides conf_compile_suffix)."""
    import jax
    import jaxlib

    import spark_tpu

    devs = jax.devices()
    fp: Dict = {
        # the ENGINE version too: a spark_tpu upgrade whose kernel
        # semantics changed without touching describe() or any
        # compile-relevant conf must not serve a pre-upgrade
        # executable off a persistent volume — the same staleness
        # class as a jaxlib upgrade, one layer up
        "spark_tpu": getattr(spark_tpu, "__version__", "dev"),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "",
        "n_devices": len(devs),
    }
    if mesh is not None:
        fp["mesh_shape"] = tuple(int(x) for x in mesh.devices.shape)
        fp["mesh_devices"] = tuple(
            int(d.id) for d in mesh.devices.flat)
    return fp


def call_signature(args) -> Tuple:
    """(treedef, leaf avals) of the stage call: the treedef carries the
    pytree STRUCTURE + aux (column names, dtypes, dictionaries — the
    exact identity `jax.jit` retraces on), the aval tuple carries what
    treedefs do not (leaf shapes/dtypes, which a shape-specialized
    `Compiled` raises on). Both must match for dispatch."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, {}))
    avals = tuple(
        (tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in leaves)
    return treedef, avals


def _sig_hash(sig: Tuple) -> str:
    """Content digest of a call signature for the entry filename.
    Pickle bytes of equal treedefs are content-deterministic across
    processes (proven by the cross-process test); a spurious mismatch
    would only cost a cache miss — the load path re-verifies equality
    before any dispatch."""
    treedef, avals = sig
    h = hashlib.sha256()
    try:
        h.update(pickle.dumps(treedef))
    except Exception:  # noqa: BLE001 — unpicklable aux: sig-less key
        h.update(repr(treedef).encode())
    h.update(repr(avals).encode())
    return h.hexdigest()


def _deserialize(entry: Dict):
    """Backend-load a validated entry's executable (the shared tail of
    the query-path load and the warm-start replay)."""
    from jax.experimental import serialize_executable as se
    return se.deserialize_and_load(
        entry["payload"], entry["in_tree"], entry["out_tree"])


def entry_hash(stage_key: str, fingerprint: Dict, sig: Tuple) -> str:
    h = hashlib.sha256()
    h.update(stage_key.encode())
    h.update(b"\x00")
    h.update(json.dumps(fingerprint, sort_keys=True,
                        default=str).encode())
    h.update(b"\x00")
    h.update(_sig_hash(sig).encode())
    return h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# The signature-dispatching stage callable
# ---------------------------------------------------------------------------


class CachedStageFn:
    """Stage-cache value wrapping deserialized/AOT `Compiled` programs:
    dispatches to the Compiled whose signature matches the call, and
    falls back to a lazily-built `jax.jit` for any other signature
    (mirroring the plain-jit entry's retrace behavior — a Compiled is
    shape- and treedef-specialized, a jit is polymorphic).

    Instances live in the sessions-shared stage cache, so service
    threads race `add`/`_jit` — both are GIL-atomic stores whose worst
    case is a duplicate compile (waived in the concurrency registry,
    the `arbiter.stage_cache` precedent)."""

    def __init__(self, make_jit=None):
        #: thunk building the polymorphic jit fallback; warm-start
        #: installs entries builder-less and the executor binds one
        #: before first use (it owns the plan needed to build it)
        self._make_jit = make_jit
        self._jit = None
        #: [(treedef, avals, Compiled)] — tiny linear scan (a stage
        #: key almost always sees exactly one signature)
        self._compiled: List[Tuple] = []

    @property
    def has_builder(self) -> bool:
        return self._make_jit is not None

    def bind_builder(self, make_jit) -> None:
        """Attach the jit-fallback builder if none is bound yet. The
        thunk must close over the built stage callable (conf + plan),
        never the QueryExecution — wrappers outlive queries in the
        shared stage cache."""
        if self._make_jit is None:
            self._make_jit = make_jit

    def add(self, sig: Tuple, compiled) -> None:
        treedef, avals = sig
        if self.compiled_for_sig(sig) is None:
            self._compiled.append((treedef, avals, compiled))

    def compiled_for_sig(self, sig: Tuple):
        treedef, avals = sig
        for td, av, compiled in self._compiled:
            if av == avals and td == treedef:
                return compiled
        return None

    def compiled_for(self, args):
        return self.compiled_for_sig(call_signature(args))

    def _fallback(self):
        if self._jit is None:
            if self._make_jit is None:
                raise RuntimeError(
                    "CachedStageFn has no jit builder bound (warm-start "
                    "entry dispatched before the executor bound one)")
            self._jit = self._make_jit()
        return self._jit

    def __call__(self, *args):
        compiled = self.compiled_for(args)
        if compiled is not None:
            return compiled(*args)
        return self._fallback()(*args)

    def lower(self, *args):
        """AOT-lowering compatibility (xla_cost.analyze_jit consumes a
        `.lower`-bearing callable)."""
        return self._fallback().lower(*args)


# ---------------------------------------------------------------------------
# The on-disk cache
# ---------------------------------------------------------------------------


class CompileCache:
    """One cache directory: entry files `cc-<hash>.pkl`, a
    `manifest.jsonl` of recently-seen stage keys, LRU-by-mtime bounded
    at `max_bytes`."""

    def __init__(self, cache_dir: str, max_bytes: int):
        self.dir = cache_dir
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()

    # -- paths ---------------------------------------------------------------

    def _entry_path(self, ehash: str) -> str:
        return os.path.join(self.dir, f"cc-{ehash}.pkl")

    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "manifest.jsonl")

    # -- load ----------------------------------------------------------------

    def load(self, stage_key: str, mesh, args, metrics=None):
        """Deserialize the entry for (stage key, env, call signature);
        None on miss. NEVER raises: a corrupt/truncated entry (or an
        injected `compile_cache_load` fault) warns, counts
        `compile_cache_corrupt`, deletes the bad file and reads as a
        miss — the caller's fresh compile then overwrites it."""
        from ..testing import faults

        fp = env_fingerprint(mesh)
        sig = call_signature(args)
        path = self._entry_path(entry_hash(stage_key, fp, sig))
        if not os.path.exists(path):
            if metrics is not None:
                metrics.counter("compile_cache_disk_misses").inc()
            return None
        t0 = time.perf_counter()
        try:
            # chaos seam: models the entry-load failure class (torn
            # write, truncated pickle, backend rejection) — fired
            # inside the guard so injected faults prove the fallback
            faults.fire("compile_cache_load")
            entry = self._read_entry(path, stage_key)
            treedef, avals = sig
            if (entry is None
                    or entry.get("fingerprint") != fp
                    or entry.get("avals") != avals
                    or entry["in_tree"] != treedef):
                # digest collision, stale layout, or another
                # environment/signature: clean miss
                if metrics is not None:
                    metrics.counter("compile_cache_disk_misses").inc()
                return None
            compiled = _deserialize(entry)
        except FileNotFoundError:
            # a concurrent process's LRU eviction won the race between
            # the exists() check and open(): a plain miss, NOT
            # corruption — routine eviction must not light up the
            # compile_cache_corrupt signal
            if metrics is not None:
                metrics.counter("compile_cache_disk_misses").inc()
            return None
        except Exception as e:  # noqa: BLE001 — never fail the query
            self._discard_corrupt(path, e, metrics,
                                  "recompiling and overwriting it")
            if metrics is not None:
                metrics.counter("compile_cache_disk_misses").inc()
            return None
        if metrics is not None:
            metrics.counter("compile_cache_disk_hits").inc()
            metrics.counter("compile_cache_deser_ms").inc(
                round((time.perf_counter() - t0) * 1e3, 3))
        return self._finish_load(path, stage_key, compiled)

    def _finish_load(self, path: str, stage_key: str, compiled):
        """LRU touch + manifest recency for a successful load."""
        # LRU recency: a loaded entry is fresh again
        with contextlib.suppress(OSError):
            os.utime(path)
        self._note_seen(stage_key, os.path.basename(path))
        return compiled

    def _read_entry(self, path: str, stage_key: str) -> Optional[Dict]:
        """Open + unpickle + format/stage-key validation — THE entry
        reader shared by the query-path load and the warm-start
        replay, so their validation can never drift. Returns None on
        a clean structural mismatch (stale layout, digest collision);
        raises on damage (caller routes to `_discard_corrupt`);
        FileNotFoundError propagates (concurrent eviction = skip)."""
        with open(path, "rb") as f:
            entry = pickle.load(f)
        if entry.get("format") != ENTRY_FORMAT \
                or entry.get("stage_key") != stage_key:
            return None
        return entry

    def _discard_corrupt(self, path: str, err, metrics,
                         followup: str) -> None:
        """ONE corrupt-entry policy for the query-path load AND the
        warm-start replay: warn, count `compile_cache_corrupt`, and
        DELETE the damaged file — so it is rewritten by the next
        fresh compile instead of re-warning on every consult (or
        every service restart) forever."""
        warnings.warn(
            f"compile cache entry {os.path.basename(path)} failed to "
            f"load ({type(err).__name__}: {err}); {followup}")
        if metrics is not None:
            metrics.counter("compile_cache_corrupt").inc()
        with contextlib.suppress(OSError):
            os.remove(path)

    # -- store ---------------------------------------------------------------

    def store(self, stage_key: str, mesh, args, compiled,
              metrics=None) -> bool:
        """Serialize + atomically publish one executable; False (with a
        warning) when the backend cannot serialize or the write fails —
        the query proceeds on the in-memory entry either way."""
        from jax.experimental import serialize_executable as se

        from .state_store import fsync_replace

        fp = env_fingerprint(mesh)
        sig = call_signature(args)
        treedef, avals = sig
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps({
                "format": ENTRY_FORMAT,
                "stage_key": stage_key,
                "fingerprint": fp,
                "avals": avals,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "payload": payload,
                "ts": time.time(),
            })
        except Exception as e:  # noqa: BLE001 — backend w/o serialization
            warnings.warn(f"compile cache: executable not serializable "
                          f"({type(e).__name__}: {e}); entry skipped")
            return False
        path = self._entry_path(entry_hash(stage_key, fp, sig))
        try:
            with self._lock:
                os.makedirs(self.dir, exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(blob)
                fsync_replace(tmp, path)
                self._manifest_append_locked(
                    stage_key, os.path.basename(path))
                self._evict_locked(keep=os.path.basename(path))
        except OSError as e:
            warnings.warn(f"compile cache write failed: {e}")
            return False
        if metrics is not None:
            metrics.counter("compile_cache_write_bytes").inc(len(blob))
        return True

    # -- bounds --------------------------------------------------------------

    def _entries_by_age(self) -> List[Tuple[float, int, str]]:
        """[(mtime, size, path)] oldest first, covering the cc-*.pkl
        entries AND the `xla/` secondary seat (JAX's persistent cache
        has no eviction of its own — the operator bounded THIS
        directory, so everything under it counts). Files vanishing
        under a concurrent process's eviction are skipped."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        paths = [os.path.join(self.dir, n) for n in names
                 if n.startswith("cc-") and n.endswith(".pkl")]
        for root, _dirs, files in os.walk(os.path.join(self.dir,
                                                       "xla")):
            paths.extend(os.path.join(root, f) for f in files)
        for path in paths:
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, path))
        out.sort()
        return out

    def _evict_locked(self, keep: str = "") -> int:
        """LRU-by-mtime down to max_bytes; the just-written entry
        (`keep`) is never its own victim even when it alone exceeds
        the bound. Returns files removed."""
        if self.max_bytes <= 0:
            return 0
        entries = self._entries_by_age()
        total = sum(size for _, size, _ in entries)
        removed = 0
        for _, size, path in entries:
            if total <= self.max_bytes:
                break
            if os.path.basename(path) == keep:
                continue
            with contextlib.suppress(OSError):
                os.remove(path)
                removed += 1
                total -= size
        return removed

    def evict(self) -> int:
        with self._lock:
            return self._evict_locked()

    # -- manifest (warm-start replay) ----------------------------------------

    def _note_seen(self, stage_key: str, file_name: str) -> None:
        try:
            with self._lock:
                self._manifest_append_locked(stage_key, file_name)
        except OSError as e:
            warnings.warn(f"compile cache manifest append failed: {e}")

    def _manifest_append_locked(self, stage_key: str,
                                file_name: str) -> None:
        os.makedirs(self.dir, exist_ok=True)
        line = json.dumps({"file": file_name, "stage_key": stage_key,
                           "ts": round(time.time(), 3)})
        path = self._manifest_path
        with open(path, "a") as f:
            f.write(line + "\n")
        # bound the append-only log: rewrite keeping the newest record
        # per entry file (atomic swap — a concurrent reader sees the
        # old or the new manifest, never a torn one). The trigger is
        # an os.stat byte threshold — appends run on the query path
        # (every store and disk hit), so counting lines by reading
        # the whole file each time would tax exactly the hot path
        # the cache exists to speed up.
        try:
            if os.path.getsize(path) <= _MANIFEST_MAX_BYTES:
                return
        except OSError:
            return
        from .state_store import fsync_replace
        records = self._read_manifest()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            # _read_manifest returns newest-first; the FILE must stay
            # chronological (oldest-first) — readers reverse it, so
            # writing newest-first here would invert every later read
            # and make compaction keep the stalest half
            for rec in reversed(records[:_MANIFEST_MAX_LINES // 2]):
                f.write(json.dumps(rec) + "\n")
        fsync_replace(tmp, path)

    def _read_manifest(self) -> List[Dict]:
        """Newest-first, unique per entry file; torn/garbage lines are
        skipped (the append is not atomic by design — losing the tail
        record costs a warm-start seed, never correctness)."""
        path = self._manifest_path
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return []
        seen = set()
        out = []
        for line in reversed(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            name = rec.get("file")
            if not name or name in seen or "stage_key" not in rec:
                continue
            seen.add(name)
            out.append(rec)
        return out

    # -- warm start ----------------------------------------------------------

    def warm_start(self, stage_cache: Dict, metrics=None,
                   max_entries: int = 256) -> int:
        """Replay the manifest of recently-seen stage keys into an
        in-memory stage cache: deserialize each entry whose environment
        fingerprint matches this process and install a (builder-less)
        `CachedStageFn` under its stage key — a restarted serving
        process opens with a hot cache. Returns entries installed.
        Never raises; unloadable entries are skipped (corrupt ones
        counted, exactly like the query-path load)."""
        import jax

        base = env_fingerprint(None)
        device_ids = {int(d.id) for d in jax.devices()}
        installed = 0
        for rec in self._read_manifest():
            if installed >= max_entries:
                break
            skey = rec["stage_key"]
            existing = stage_cache.get(skey)
            if existing is not None \
                    and not isinstance(existing, CachedStageFn):
                continue  # a plain jit already serves this key
            path = os.path.join(self.dir, rec["file"])
            if not os.path.exists(path):
                continue
            try:
                entry = self._read_entry(path, skey)
                if entry is None:
                    continue
                if isinstance(existing, CachedStageFn) \
                        and existing.compiled_for_sig(
                            (entry["in_tree"], entry["avals"])) \
                        is not None:
                    continue  # this signature is already warm
                # compare BASE fields only; mesh entries additionally
                # require their gang's device ids to exist here
                efp = dict(entry.get("fingerprint") or {})
                efp.pop("mesh_shape", None)
                mesh_ids = efp.pop("mesh_devices", ())
                if efp != base:
                    continue  # other toolchain/backend: not ours
                if mesh_ids and not set(mesh_ids) <= device_ids:
                    continue  # gang over devices this process lacks
                compiled = _deserialize(entry)
            except FileNotFoundError:
                continue  # concurrent eviction: plain skip, not corrupt
            except Exception as e:  # noqa: BLE001 — skip, never raise
                self._discard_corrupt(
                    path, e, metrics,
                    "warm start skips it (the next fresh compile of "
                    "its stage rewrites the entry)")
                continue
            fn = stage_cache.get(skey)
            if not isinstance(fn, CachedStageFn):
                fn = CachedStageFn()
                stage_cache[skey] = fn
            fn.add((entry["in_tree"], entry["avals"]), compiled)
            installed += 1
            # LRU recency, exactly like the query-path load: a service
            # that only ever opens via warm start must not see its
            # hottest entries become the oldest-mtime eviction victims
            with contextlib.suppress(OSError):
                os.utime(path)
        if metrics is not None and installed:
            metrics.counter("compile_cache_warm_entries").inc(installed)
        return installed


# ---------------------------------------------------------------------------
# Conf-driven accessor + warm-start entry points
# ---------------------------------------------------------------------------

#: process-global instances per (abs dir, maxBytes). GIL-atomic dict
#: get/set (guarded-by waiver): a duplicate CompileCache for one dir is
#: equivalent — every write goes through atomic renames and every read
#: tolerates concurrent eviction, so two instances' locks merely guard
#: their own manifest/eviction bookkeeping.
_CACHES: Dict[Tuple[str, int], CompileCache] = {}


def get_cache(conf) -> Optional[CompileCache]:
    """The conf-selected CompileCache, or None when disabled (the
    default) or pointed at no directory."""
    if not bool(conf.get(ENABLED_KEY)):
        return None
    d = str(conf.get(DIR_KEY) or "").strip()
    if not d:
        return None
    key = (os.path.abspath(d), int(conf.get(MAX_BYTES_KEY)))
    cc = _CACHES.get(key)
    if cc is None:
        cc = _CACHES[key] = CompileCache(*key)
        _wire_jax_cache(key[0])
    return cc


def _wire_jax_cache(base_dir: str) -> None:
    """Secondary seat: point JAX's native compilation cache at
    `<dir>/xla` unless the operator already configured one. It keys on
    HLO + compile options, so a fingerprint/signature miss that
    re-lowers an unchanged program can still skip the backend compile
    (trace + lower are still paid — the executable cache above is the
    primary seat). Best-effort: support varies by platform/version."""
    try:
        import jax
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(base_dir, "xla"))
    except Exception as e:  # noqa: BLE001 — advisory only
        warnings.warn(f"compile cache: could not wire "
                      f"jax_compilation_cache_dir ({e})")


def warm_start(stage_cache: Dict, conf, metrics=None) -> int:
    """Module-level warm-start over the conf-selected cache (the
    `session.warmup()` / `SqlService.start()` entry point). 0 when the
    cache is disabled."""
    cc = get_cache(conf)
    if cc is None:
        return 0
    return cc.warm_start(stage_cache, metrics=metrics)

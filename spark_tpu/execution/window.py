"""Window function kernels: segmented scans over one sorted permutation.

The reference's `execution/window/WindowExec.scala` (1,389-LoC package)
streams rows per partition through frame processors; here one
`lax.sort` orders rows by (partition keys, order keys) and every window
function lowers to vectorized segmented scans over that order —
cumulative sums/max tricks instead of per-row loops, the shape the
VPU executes at memory bandwidth. Outputs scatter back through the
permutation so the operator preserves input row order.

Supported (the reference's most-used set):
- row_number, rank, dense_rank
- lag/lead with literal offset + default
- sum/count/min/max/avg over the partition: whole-partition frame when
  no ORDER BY, and the Spark default `RANGE UNBOUNDED PRECEDING ..
  CURRENT ROW` (peer rows included) when ordered.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column
from ..expr import SortOrder, Vec
from . import sort as sort_kernels


def _segment_starts(sorted_key_ops: List, cap: int, valid_sorted):
    """Boolean: row i starts a new partition segment (first valid row or
    any partition-key operand differs from the previous row)."""
    diff = jnp.zeros((cap,), jnp.bool_)
    for op in sorted_key_ops:
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.arange(cap) == 0
    return (first | diff) & valid_sorted


def _cummax_where(flag, values, neutral):
    """Inclusive cumulative max of `values` where flag else neutral."""
    return jax.lax.cummax(jnp.where(flag, values, neutral))


def _seg_start_pos(starts, cap):
    """For each row, the position of its segment's first row."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    return _cummax_where(starts, iota, jnp.int32(0))


def _peer_change(starts, sorted_order_ops, cap):
    """Row i begins a new peer group (segment start or any order-key
    operand differs from the previous row)."""
    change = starts
    for op in sorted_order_ops:
        change = change | (op != jnp.roll(op, 1))
    return change


def _last_peer_pos(change, cap):
    """For each row, the position of the LAST row of its peer group:
    one before the next change point (cap-1 when none follows)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    nxt = jnp.where(change, iota, cap)
    # suffix-min of nxt over positions > i
    suffix = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.concatenate([nxt[1:], jnp.array([cap], jnp.int32)]))))
    return jnp.minimum(suffix, cap) - 1


def row_number(starts, cap):
    iota = jnp.arange(cap, dtype=jnp.int32)
    return (iota - _seg_start_pos(starts, cap) + 1).astype(jnp.int64)


def rank(starts, change, cap):
    iota = jnp.arange(cap, dtype=jnp.int32)
    last_change = _cummax_where(change, iota, jnp.int32(0))
    return (last_change - _seg_start_pos(starts, cap) + 1).astype(jnp.int64)


def dense_rank(starts, change, cap):
    cum = jnp.cumsum(change.astype(jnp.int32))
    at_start = jnp.take(cum, _seg_start_pos(starts, cap))
    return (cum - at_start + 1).astype(jnp.int64)


def shift_in_segment(values, validity, seg_id, offset: int, default,
                     cap: int):
    """lag (offset>0) / lead (offset<0) within the partition segment."""
    shifted = jnp.roll(values, offset)
    seg_shifted = jnp.roll(seg_id, offset)
    iota = jnp.arange(cap)
    in_range = (iota >= offset) if offset > 0 else (iota < cap + offset)
    same = (seg_shifted == seg_id) & in_range
    if validity is not None:
        v_shifted = jnp.roll(validity, offset)
    else:
        v_shifted = jnp.ones((cap,), jnp.bool_)
    if default is None:
        out_valid = same & v_shifted
        out = jnp.where(same, shifted, jnp.zeros((), values.dtype))
    else:
        out = jnp.where(same, shifted,
                        jnp.full((), default, values.dtype))
        out_valid = ~same | v_shifted
    return out, out_valid


def windowed_agg(kind: str, values, validity, gid, num_segments: int,
                 starts, change, ordered: bool, cap: int):
    """sum/count/min/max/avg over the frame. Unordered -> whole
    partition; ordered -> running up to the last PEER row (the Spark
    default RANGE frame)."""
    mask = validity if validity is not None else jnp.ones((cap,), jnp.bool_)
    x = values
    if kind in ("sum", "avg"):
        contrib = jnp.where(mask, x, jnp.zeros((), x.dtype))
    elif kind == "count":
        contrib = mask.astype(jnp.int64)
    elif kind == "min":
        contrib = jnp.where(mask, x, _max_of(x.dtype))
    else:
        contrib = jnp.where(mask, x, _min_of(x.dtype))
    cnt_contrib = mask.astype(jnp.int64)

    if not ordered:
        if kind in ("min", "max"):
            red = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
            seg = red(contrib, gid, num_segments=num_segments + 1)[:-1]
            out = jnp.take(seg, jnp.clip(gid, 0, num_segments - 1))
            seg_cnt = jax.ops.segment_sum(cnt_contrib, gid,
                                          num_segments=num_segments + 1)[:-1]
            cnt = jnp.take(seg_cnt, jnp.clip(gid, 0, num_segments - 1))
            return out, cnt
        seg = jax.ops.segment_sum(contrib, gid,
                                  num_segments=num_segments + 1)[:-1]
        seg_cnt = jax.ops.segment_sum(cnt_contrib, gid,
                                      num_segments=num_segments + 1)[:-1]
        out = jnp.take(seg, jnp.clip(gid, 0, num_segments - 1))
        cnt = jnp.take(seg_cnt, jnp.clip(gid, 0, num_segments - 1))
        return out, cnt

    start_pos = _seg_start_pos(starts, cap)
    last_peer = _last_peer_pos(change, cap)
    runc = jnp.cumsum(cnt_contrib)
    cnt_at_start = jnp.take(runc, start_pos) - jnp.take(cnt_contrib,
                                                        start_pos)
    cnt = jnp.take(runc, last_peer) - cnt_at_start
    if kind in ("min", "max"):
        run = _segmented_running(contrib, start_pos, cap, kind)
        return jnp.take(run, last_peer), cnt
    run = jnp.cumsum(contrib.astype(
        jnp.float64 if jnp.issubdtype(contrib.dtype, jnp.floating)
        else jnp.int64))
    at_start = jnp.take(run, start_pos) - jnp.take(contrib, start_pos)
    frame = jnp.take(run, last_peer) - at_start
    return frame.astype(contrib.dtype), cnt


def _segmented_running(contrib, start_pos, cap: int, kind: str):
    """Running min/max since the segment start, via a log-step scan
    (Hillis-Steele) that refuses to look past start_pos."""
    op = jnp.minimum if kind == "min" else jnp.maximum
    iota = jnp.arange(cap, dtype=jnp.int32)
    acc = contrib
    shift = 1
    while shift < cap:
        prev = jnp.roll(acc, shift)
        ok = iota - shift >= start_pos
        acc = jnp.where(ok, op(acc, prev), acc)
        shift <<= 1
    return acc


def _max_of(dt):
    return np.array(np.finfo(dt).max if jnp.issubdtype(dt, jnp.floating)
                    else np.iinfo(dt).max, dt)


def _min_of(dt):
    return np.array(np.finfo(dt).min if jnp.issubdtype(dt, jnp.floating)
                    else np.iinfo(dt).min, dt)


# ---------------------------------------------------------------------------
# Specified frames: ROWS/RANGE BETWEEN (reference: WindowExec.scala:36
# frame processors — SlidingWindowFunctionFrame & friends as vectorized
# prefix sums + sparse-table range queries instead of per-row loops)
# ---------------------------------------------------------------------------

from ..window import UNBOUNDED_FOLLOWING, UNBOUNDED_PRECEDING  # noqa: E402


def _seg_end_pos(starts, cap):
    """Position of the LAST row of each row's partition segment."""
    return _last_peer_pos(starts, cap)


def _first_peer_pos(change, cap):
    iota = jnp.arange(cap, dtype=jnp.int32)
    return _cummax_where(change, iota, jnp.int32(0))


def _searchsorted_seg(keys, seg_lo, seg_hi, targets, side: str, cap: int):
    """Vectorized per-row binary search WITHIN each row's segment:
    first position p in [seg_lo, seg_hi+1] with keys[p] >= target
    (side='left') or > target (side='right'). keys must be ascending
    within every segment (they are: rows sort by partition then key)."""
    lo = seg_lo.astype(jnp.int32)
    hi = (seg_hi + 1).astype(jnp.int32)
    steps = max(1, int(np.ceil(np.log2(max(cap, 2)))) + 1)
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        kv = jnp.take(keys, jnp.clip(mid, 0, cap - 1))
        go_right = (kv < targets) if side == "left" else (kv <= targets)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def frame_bounds(frame, starts, change, cap,
                 ordered: bool, n_valid=None,
                 range_key=None, range_key_valid=None):
    """Per-row INCLUSIVE sorted-position bounds [lo, hi] of the frame.

    frame: None | ("rows"|"range", start, end) with UNBOUNDED sentinels.
    `n_valid` is the live-row count: dead (filtered) rows sort to the
    global tail, so the LAST segment's end must clamp to n_valid-1 or
    frames would span garbage rows. For "range", `range_key` is the
    single ascending numeric order key in sorted order, SANITIZED to be
    monotone (NULL-key and dead rows carry ±sentinels — see
    sanitize_range_key); NULL-key rows take their peer group as the
    frame (nulls sort together)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    seg_lo = _seg_start_pos(starts, cap)
    seg_hi = _seg_end_pos(starts, cap)
    if n_valid is not None:
        seg_hi = jnp.minimum(seg_hi, jnp.maximum(n_valid - 1, 0)
                             .astype(seg_hi.dtype))
    if frame is None:
        if not ordered:
            return seg_lo, seg_hi
        return seg_lo, jnp.minimum(_last_peer_pos(change, cap), seg_hi)
    kind, a, b = frame
    if kind == "rows":
        # offsets past the capacity behave as unbounded (they clamp to
        # the partition anyway), keeping arbitrary user offsets out of
        # the int32 index arithmetic
        a = max(a, -cap - 1)
        b = min(b, cap + 1)
        lo = seg_lo if a <= UNBOUNDED_PRECEDING else \
            jnp.maximum(seg_lo, iota + jnp.int32(a))
        hi = seg_hi if b >= UNBOUNDED_FOLLOWING else \
            jnp.minimum(seg_hi, iota + jnp.int32(b))
        return lo, hi
    # RANGE: value-space offsets on the (ascending, sanitized) order key
    key = range_key
    if a <= UNBOUNDED_PRECEDING:
        lo = seg_lo
    else:
        lo = _searchsorted_seg(key, seg_lo, seg_hi, key + a, "left", cap)
    if b >= UNBOUNDED_FOLLOWING:
        hi = seg_hi
    else:
        hi = _searchsorted_seg(key, seg_lo, seg_hi, key + b,
                               "right", cap) - 1
    if range_key_valid is not None:
        # NULL order keys: the frame is the row's peer group
        fp = _first_peer_pos(change, cap)
        lp = jnp.minimum(_last_peer_pos(change, cap), seg_hi)
        lo = jnp.where(range_key_valid, lo, fp)
        hi = jnp.where(range_key_valid, hi, lp)
    return lo, hi


def sanitize_range_key(key, key_valid, valid_sorted, nulls_first: bool):
    """Make the sorted order key monotone over every [seg_lo, seg_hi]
    search range: NULL-key rows (which sort to the segment's head for
    NULLS FIRST, tail for NULLS LAST) and dead rows (global tail) carry
    raw garbage values that would break the binary search. Replace them
    with the matching ±extreme sentinel; the searched targets (finite
    key ± offset) never land inside the sentinel regions, and NULL rows'
    own bounds are overridden to their peer group afterwards."""
    if jnp.issubdtype(key.dtype, jnp.integer):
        key = key.astype(jnp.int64)
        lo_s = jnp.iinfo(jnp.int64).min
        hi_s = jnp.iinfo(jnp.int64).max
    else:
        key = key.astype(jnp.float64)
        lo_s = -jnp.inf
        hi_s = jnp.inf
    dead_or_null = ~valid_sorted if key_valid is None else \
        (~valid_sorted | ~key_valid)
    null_sentinel = lo_s if nulls_first else hi_s
    key = jnp.where(dead_or_null & valid_sorted, null_sentinel, key)
    key = jnp.where(~valid_sorted, hi_s, key)
    return key


def _prefix_frame(contrib, lo, hi, cap):
    """sum over inclusive positions [lo, hi] via one prefix scan."""
    acc = contrib.astype(
        jnp.float64 if jnp.issubdtype(contrib.dtype, jnp.floating)
        else jnp.int64)
    pref = jnp.cumsum(acc)
    hi_c = jnp.clip(hi, 0, cap - 1)
    lo_c = jnp.clip(lo, 0, cap - 1)
    total = (jnp.take(pref, hi_c) - jnp.take(pref, lo_c)
             + jnp.take(acc, lo_c))
    return jnp.where(hi < lo, jnp.zeros((), acc.dtype), total)


def _rmq_frame(contrib, lo, hi, cap: int, kind: str,
               max_len: Optional[int] = None):
    """min/max over inclusive [lo, hi] via a sparse table: O(1) per-row
    query — the vectorized seat of the reference's sliding frame
    processors. `max_len` (known for finite ROWS frames) caps the table
    at log2(max_len)+1 levels, so a small sliding window costs O(n)
    memory instead of O(n log n) (code-review r5)."""
    op = jnp.minimum if kind == "min" else jnp.maximum
    iota = jnp.arange(cap, dtype=jnp.int32)
    bound = cap if max_len is None else min(cap, max(max_len, 1))
    levels = [contrib]
    k = 1
    while (1 << k) <= bound:
        half = 1 << (k - 1)
        prev = levels[-1]
        levels.append(op(prev, jnp.take(
            prev, jnp.clip(iota + half, 0, cap - 1))))
        k += 1
    stacked = jnp.stack(levels)            # [L, cap]
    length = jnp.maximum(hi - lo + 1, 1)
    lv = jnp.floor(jnp.log2(length.astype(jnp.float64))).astype(jnp.int32)
    lv = jnp.clip(lv, 0, len(levels) - 1)
    flat = stacked.reshape(-1)
    lo_c = jnp.clip(lo, 0, cap - 1)
    right = jnp.clip(hi - (1 << lv.astype(jnp.int64)).astype(jnp.int32) + 1,
                     0, cap - 1)
    x1 = jnp.take(flat, lv * cap + lo_c)
    x2 = jnp.take(flat, lv * cap + right)
    return op(x1, x2)


def framed_agg(kind: str, values, validity, lo, hi, cap: int,
               max_len: Optional[int] = None):
    """sum/count/min/max over explicit per-row frame bounds. Returns
    (value, count-in-frame); empty frames report count 0 (NULL)."""
    mask = validity if validity is not None else jnp.ones((cap,), jnp.bool_)
    cnt = _prefix_frame(mask.astype(jnp.int64), lo, hi, cap)
    if kind == "count":
        return cnt, cnt
    if kind in ("sum",):
        contrib = jnp.where(mask, values, jnp.zeros((), values.dtype))
        return _prefix_frame(contrib, lo, hi, cap).astype(values.dtype), cnt
    neutral = _max_of(values.dtype) if kind == "min" else _min_of(values.dtype)
    contrib = jnp.where(mask, values, neutral)
    return _rmq_frame(contrib, lo, hi, cap, kind, max_len), cnt

"""Window function kernels: segmented scans over one sorted permutation.

The reference's `execution/window/WindowExec.scala` (1,389-LoC package)
streams rows per partition through frame processors; here one
`lax.sort` orders rows by (partition keys, order keys) and every window
function lowers to vectorized segmented scans over that order —
cumulative sums/max tricks instead of per-row loops, the shape the
VPU executes at memory bandwidth. Outputs scatter back through the
permutation so the operator preserves input row order.

Supported (the reference's most-used set):
- row_number, rank, dense_rank
- lag/lead with literal offset + default
- sum/count/min/max/avg over the partition: whole-partition frame when
  no ORDER BY, and the Spark default `RANGE UNBOUNDED PRECEDING ..
  CURRENT ROW` (peer rows included) when ordered.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column
from ..expr import SortOrder, Vec
from . import sort as sort_kernels


def _segment_starts(sorted_key_ops: List, cap: int, valid_sorted):
    """Boolean: row i starts a new partition segment (first valid row or
    any partition-key operand differs from the previous row)."""
    diff = jnp.zeros((cap,), jnp.bool_)
    for op in sorted_key_ops:
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.arange(cap) == 0
    return (first | diff) & valid_sorted


def _cummax_where(flag, values, neutral):
    """Inclusive cumulative max of `values` where flag else neutral."""
    return jax.lax.cummax(jnp.where(flag, values, neutral))


def _seg_start_pos(starts, cap):
    """For each row, the position of its segment's first row."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    return _cummax_where(starts, iota, jnp.int32(0))


def _peer_change(starts, sorted_order_ops, cap):
    """Row i begins a new peer group (segment start or any order-key
    operand differs from the previous row)."""
    change = starts
    for op in sorted_order_ops:
        change = change | (op != jnp.roll(op, 1))
    return change


def _last_peer_pos(change, cap):
    """For each row, the position of the LAST row of its peer group:
    one before the next change point (cap-1 when none follows)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    nxt = jnp.where(change, iota, cap)
    # suffix-min of nxt over positions > i
    suffix = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.concatenate([nxt[1:], jnp.array([cap], jnp.int32)]))))
    return jnp.minimum(suffix, cap) - 1


def row_number(starts, cap):
    iota = jnp.arange(cap, dtype=jnp.int32)
    return (iota - _seg_start_pos(starts, cap) + 1).astype(jnp.int64)


def rank(starts, change, cap):
    iota = jnp.arange(cap, dtype=jnp.int32)
    last_change = _cummax_where(change, iota, jnp.int32(0))
    return (last_change - _seg_start_pos(starts, cap) + 1).astype(jnp.int64)


def dense_rank(starts, change, cap):
    cum = jnp.cumsum(change.astype(jnp.int32))
    at_start = jnp.take(cum, _seg_start_pos(starts, cap))
    return (cum - at_start + 1).astype(jnp.int64)


def shift_in_segment(values, validity, seg_id, offset: int, default,
                     cap: int):
    """lag (offset>0) / lead (offset<0) within the partition segment."""
    shifted = jnp.roll(values, offset)
    seg_shifted = jnp.roll(seg_id, offset)
    iota = jnp.arange(cap)
    in_range = (iota >= offset) if offset > 0 else (iota < cap + offset)
    same = (seg_shifted == seg_id) & in_range
    if validity is not None:
        v_shifted = jnp.roll(validity, offset)
    else:
        v_shifted = jnp.ones((cap,), jnp.bool_)
    if default is None:
        out_valid = same & v_shifted
        out = jnp.where(same, shifted, jnp.zeros((), values.dtype))
    else:
        out = jnp.where(same, shifted,
                        jnp.full((), default, values.dtype))
        out_valid = ~same | v_shifted
    return out, out_valid


def windowed_agg(kind: str, values, validity, gid, num_segments: int,
                 starts, change, ordered: bool, cap: int):
    """sum/count/min/max/avg over the frame. Unordered -> whole
    partition; ordered -> running up to the last PEER row (the Spark
    default RANGE frame)."""
    mask = validity if validity is not None else jnp.ones((cap,), jnp.bool_)
    x = values
    if kind in ("sum", "avg"):
        contrib = jnp.where(mask, x, jnp.zeros((), x.dtype))
    elif kind == "count":
        contrib = mask.astype(jnp.int64)
    elif kind == "min":
        contrib = jnp.where(mask, x, _max_of(x.dtype))
    else:
        contrib = jnp.where(mask, x, _min_of(x.dtype))
    cnt_contrib = mask.astype(jnp.int64)

    if not ordered:
        if kind in ("min", "max"):
            red = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
            seg = red(contrib, gid, num_segments=num_segments + 1)[:-1]
            out = jnp.take(seg, jnp.clip(gid, 0, num_segments - 1))
            seg_cnt = jax.ops.segment_sum(cnt_contrib, gid,
                                          num_segments=num_segments + 1)[:-1]
            cnt = jnp.take(seg_cnt, jnp.clip(gid, 0, num_segments - 1))
            return out, cnt
        seg = jax.ops.segment_sum(contrib, gid,
                                  num_segments=num_segments + 1)[:-1]
        seg_cnt = jax.ops.segment_sum(cnt_contrib, gid,
                                      num_segments=num_segments + 1)[:-1]
        out = jnp.take(seg, jnp.clip(gid, 0, num_segments - 1))
        cnt = jnp.take(seg_cnt, jnp.clip(gid, 0, num_segments - 1))
        return out, cnt

    start_pos = _seg_start_pos(starts, cap)
    last_peer = _last_peer_pos(change, cap)
    runc = jnp.cumsum(cnt_contrib)
    cnt_at_start = jnp.take(runc, start_pos) - jnp.take(cnt_contrib,
                                                        start_pos)
    cnt = jnp.take(runc, last_peer) - cnt_at_start
    if kind in ("min", "max"):
        run = _segmented_running(contrib, start_pos, cap, kind)
        return jnp.take(run, last_peer), cnt
    run = jnp.cumsum(contrib.astype(
        jnp.float64 if jnp.issubdtype(contrib.dtype, jnp.floating)
        else jnp.int64))
    at_start = jnp.take(run, start_pos) - jnp.take(contrib, start_pos)
    frame = jnp.take(run, last_peer) - at_start
    return frame.astype(contrib.dtype), cnt


def _segmented_running(contrib, start_pos, cap: int, kind: str):
    """Running min/max since the segment start, via a log-step scan
    (Hillis-Steele) that refuses to look past start_pos."""
    op = jnp.minimum if kind == "min" else jnp.maximum
    iota = jnp.arange(cap, dtype=jnp.int32)
    acc = contrib
    shift = 1
    while shift < cap:
        prev = jnp.roll(acc, shift)
        ok = iota - shift >= start_pos
        acc = jnp.where(ok, op(acc, prev), acc)
        shift <<= 1
    return acc


def _max_of(dt):
    return np.array(np.finfo(dt).max if jnp.issubdtype(dt, jnp.floating)
                    else np.iinfo(dt).max, dt)


def _min_of(dt):
    return np.array(np.finfo(dt).min if jnp.issubdtype(dt, jnp.floating)
                    else np.iinfo(dt).min, dt)

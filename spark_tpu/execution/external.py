"""Out-of-core host-egress execution: ORDER BY / LIMIT / plain
materialization over scans that exceed the device memory budget.

The reference handles over-memory sorts and materializations with
spillable operators on executor disk (`UnsafeExternalSorter.java:1`,
`ExternalAppendOnlyMap.scala:55`, `SortExec.scala:40`). The TPU-native
inversion: chunks of the probe scan stream through the jitted
filter/project/join chain on device, and the HOST (RAM + Arrow buffers)
plays the spill tier:

- ``LIMIT n``      -> stream chunks until n live rows have spilled;
- ``ORDER BY + LIMIT`` -> per-chunk device top-n (sort+limit fused into
  the chunk program), then one final device sort+limit over the
  concatenated (n_chunks x n, small) spill — a tournament reduction;
- ``ORDER BY``     -> spill every replayed chunk, then one host-side
  pyarrow sort over the spilled runs (the k-way-merge seat; order keys
  must be output columns) honoring ASC/DESC + NULLS FIRST/LAST;
- plain chain      -> spill every replayed chunk and concatenate.

Engages only when the scan cannot stay device-resident: its estimate
exceeds the per-query ``spark_tpu.sql.memory.deviceBudget``, or the
cross-query arbiter (service/arbiter.py) denied the residency lease
from the shared ``spark_tpu.service.hbmBudget`` pool — in-budget
queries keep whole-input residency and device sorts.

``SpillableKeyedState`` at the bottom is the same host-as-spill-tier
inversion for STREAMING aggregate state (the
`RocksDBStateStoreProvider` seat): keyed event-time state that has
outgrown its residency budget lives hash-partitioned on disk between
triggers, merged partition-at-a-time, while the delta/snapshot state
store keeps committing the same full frames — durability and crash
recovery are byte-identical to the resident path.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import jax
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..columnar import Batch, bucket_capacity
from ..plan import physical as P
from .recovery import ChunkRetrier
from .streaming_agg import (CHUNK_ROWS_KEY, _CHUNKABLE_JOINS,
                            _replay_chain, apply_join_overflow,
                            prepare_chunk_joins)


def _match_shape(plan: P.PhysicalPlan):
    """[LimitExec] [SortExec] (Project|Filter|chunkable Join)* Scan."""
    limit = None
    sort = None
    node = plan
    if isinstance(node, P.LimitExec):
        limit = node
        node = node.child
    if isinstance(node, P.SortExec):
        sort = node
        node = node.child
    chain: List[P.PhysicalPlan] = []
    while True:
        if isinstance(node, (P.ProjectExec, P.FilterExec)):
            chain.append(node)
            node = node.children[0]
        elif isinstance(node, P.RuntimeFilterExec):
            # pure pruning optimization: safe to drop in the chunked
            # replay (the join re-checks every key)
            node = node.children[0]
        elif isinstance(node, P.JoinExec) and node.how in _CHUNKABLE_JOINS:
            chain.append(node)
            node = node.children[0]
        else:
            break
    if not isinstance(node, P.ScanExec):
        return None
    return limit, sort, chain, node


def _host_sort_keys(sort: P.SortExec, schema) -> Optional[Tuple]:
    """SortOrders -> (pyarrow (name, order) keys, null_placement), or
    None when any key is a computed expression (host merge needs the key
    as a spilled output column) or null placements are mixed (pyarrow's
    SortOptions has ONE null_placement for all keys)."""
    from ..expr import Alias, ColumnRef
    keys = []
    placements = []
    names = set(schema.names)
    for o in sort.orders:
        e = o.child
        while isinstance(e, Alias):
            e = e.child
        if not isinstance(e, ColumnRef) or e._name not in names:
            return None
        keys.append((e._name,
                     "ascending" if o.ascending else "descending"))
        placements.append("at_start" if o.nulls_first else "at_end")
    if not keys or len(set(placements)) > 1:
        return None  # nothing to merge by / pyarrow can't express it
    return keys, placements[0]


def try_external_collect(session, plan: P.PhysicalPlan, conf,
                         cache: Optional[dict] = None,
                         recovery=None) -> Optional[pa.Table]:
    from ..service.arbiter import admit_scan_resident, out_of_core_active
    if not out_of_core_active(conf):
        return None
    from ..parallel.mesh import get_mesh
    if get_mesh(conf) is not None:
        return None  # the mesh streaming drivers own distributed runs
    m = _match_shape(plan)
    if m is None:
        return None
    limit, sort, chain, leaf = m
    if not hasattr(leaf.source, "load_chunks"):
        return None
    if admit_scan_resident(conf, leaf):
        return None  # fits resident (per-query budget or leased from
        # the shared arbiter pool): the normal path keeps it on device

    # pure ORDER BY (no limit) merges on host: keys must be columns
    host_keys = None
    if sort is not None and limit is None:
        host_keys = _host_sort_keys(sort, plan.schema())
        if host_keys is None:
            return None

    from ..io.sources import maybe_prefetch
    chunk_rows = int(conf.get(CHUNK_ROWS_KEY))
    chunks = maybe_prefetch(
        leaf.source.load_chunks(leaf.required_columns,
                                leaf.pushed_filters, chunk_rows),
        conf, recovery)
    first = next(iter(chunks), None)
    if first is None:
        return None

    joins, builds, _saved = prepare_chunk_joins(
        chain, conf, first.capacity, recovery)

    topn = sort is not None and limit is not None

    def make_update():
        from .streaming_agg import conf_compile_suffix
        key = (f"ext_collect:{plan.describe()}:{chunk_rows}"
               + conf_compile_suffix(conf))
        fn = cache.get(key) if cache is not None else None
        if fn is None:
            def update(b, bb):
                ctx = P.ExecContext(conf)
                b = _replay_chain(chain, ctx, b, bb)
                if topn:
                    # fuse the chunk's top-n into the device program:
                    # sorting compacts the selection, limit masks to n
                    b = sort.compute(ctx, [b])
                    b = limit.compute(ctx, [b])
                return b, ctx.flags, ctx.metrics

            fn = jax.jit(update)
            if cache is not None:
                cache[key] = fn
        return fn

    update_fn = make_update()

    def run_chunk(b):
        nonlocal update_fn
        for _attempt in range(8):
            out, flags, metrics = update_fn(b, builds)
            flags, metrics = jax.device_get((flags, metrics))
            if not apply_join_overflow(flags, metrics, joins):
                return out
            # describe() changed with the grown caps: re-jit, retry
            update_fn = make_update()
        raise RuntimeError("external-collect join capacity did not "
                           "converge")

    # chunk-granular retry (execution/recovery.py): a transient fault
    # replays only the failed chunk — nothing already spilled re-runs
    retrier = ChunkRetrier(conf, recovery)
    spilled: List[pa.Table] = []
    total_rows = 0
    ci = 0
    b = first
    try:
        while b is not None:
            t = retrier.run(lambda bb=b: run_chunk(bb).to_arrow(),
                            chunk=ci)
            spilled.append(t)
            total_rows += t.num_rows
            if limit is not None and sort is None \
                    and total_rows >= limit.n:
                break  # plain LIMIT: enough live rows spilled
            ci += 1
            b = next(chunks, None)  # ingest un-retried: see ChunkRetrier
    finally:
        if hasattr(chunks, "close"):
            # early LIMIT break, a fault, or a cancellation unwinding
            # mid-stream: release + JOIN the prefetch worker (it may
            # hold one decoded chunk against a full queue) — no ingest
            # daemon may outlive its query
            chunks.close()

    table = pa.concat_tables(spilled, promote_options="permissive")

    if topn:
        # tournament final: one small device sort+limit over the
        # concatenated per-chunk top-n spills
        ctx = P.ExecContext(conf)
        b = Batch.from_arrow(table)
        b = sort.compute(ctx, [b])
        b = limit.compute(ctx, [b])
        return b.to_arrow()
    if sort is not None:
        keys, placement = host_keys
        idx = pc.sort_indices(
            table, options=pc.SortOptions(sort_keys=keys,
                                          null_placement=placement))
        return table.take(idx)
    if limit is not None:
        return table.slice(0, limit.n)
    return table


# ---------------------------------------------------------------------------
# Host-spillable keyed state (streaming event-time aggregation)
# ---------------------------------------------------------------------------

class SpillableKeyedState:
    """Hash-partitioned parquet working set for event-time streaming
    state that exceeds `spark_tpu.streaming.state.spillBytes`.

    The contract that keeps exactly-once trivial: partitions hold ONLY
    the COMMITTED state. A trigger's merge is pure — `merge` reads the
    partitions the batch's keys hash to and returns the merged full
    frame WITHOUT writing anything; the partitions move only in
    `adopt`, which the query calls strictly AFTER its commit-log write
    (the same place the resident path adopts its pending frame). A
    crash anywhere therefore leaves the partitions at (or rebuildable
    from) a committed version, and recovery just `reset`s them from
    the store's last committed frame.

    `state_spill` (testing/faults.py) fires before every partition
    write; written bytes count in `streaming_spill_bytes`. The state
    store never sees this class — it keeps diffing full frames, so the
    persisted deltas/snapshots are identical to a resident run.

    Thread-confined: owned and driven by the query's trigger thread
    (or the manual process_available caller), never shared."""

    def __init__(self, path: str, key_cols: List[str], nparts: int,
                 metrics=None):
        self.path = path
        self.key_cols = list(key_cols)
        self.nparts = max(1, int(nparts))
        self.metrics = metrics
        os.makedirs(path, exist_ok=True)

    def _part_path(self, pid: int) -> str:
        return os.path.join(self.path, f"part-{pid:04d}.parquet")

    def _part_ids(self, pdf) -> "np.ndarray":
        """Stable partition id per row: hash the key columns' string
        forms (stable across processes, unlike Python's seeded
        hash())."""
        import pandas as pd
        key = pdf[self.key_cols[0]].astype(str)
        for c in self.key_cols[1:]:
            key = key + "\x00" + pdf[c].astype(str)
        return (pd.util.hash_pandas_object(key, index=False).to_numpy()
                % self.nparts).astype(np.int64)

    def touched_by(self, pdf) -> List[int]:
        """Partition ids a frame's keys hash to — the eviction path
        uses this to extend a trigger's touched set with the
        partitions that LOST rows (emitted-and-dropped groups)."""
        if pdf is None or not len(pdf):
            return []
        return sorted(int(p) for p in np.unique(self._part_ids(pdf)))

    def _read_part(self, pid: int):
        import pandas as pd
        p = self._part_path(pid)
        if not os.path.exists(p):
            return None
        pdf = pd.read_parquet(p)
        return pdf if len(pdf) else None

    def _write_part(self, pid: int, pdf) -> None:
        """One partition write = one spill unit: seam first (nothing
        written when an armed rule kills here), then fsync + atomic
        rename like every other checkpoint artifact."""
        import pyarrow.parquet as pq
        from ..testing import faults
        from .state_store import fsync_replace
        faults.fire("state_spill")
        full = self._part_path(pid)
        tmp = full + ".tmp"
        pq.write_table(
            pa.Table.from_pandas(pdf, preserve_index=False), tmp)
        fsync_replace(tmp, full)
        if self.metrics is not None:
            self.metrics.counter("streaming_spill_bytes").inc(
                os.path.getsize(full))

    def reset(self, full_pdf) -> None:
        """Rewrite EVERY partition from a committed full frame —
        engagement and crash recovery (partitions are a working set,
        the state store stays the durability tier)."""
        import pandas as pd
        if full_pdf is None:
            full_pdf = pd.DataFrame(columns=self.key_cols)
        pids = self._part_ids(full_pdf) if len(full_pdf) else None
        for pid in range(self.nparts):
            part = full_pdf.iloc[0:0] if pids is None \
                else full_pdf[pids == pid]
            self._write_part(pid, part.reset_index(drop=True))

    def materialize(self):
        """The full committed frame, concatenated from the partitions
        (the transient host materialization the persistence diff needs
        each trigger — the same O(state) host cost the resident path
        already pays; residency BETWEEN triggers is what spill buys)."""
        import pandas as pd
        frames = [f for f in (self._read_part(p)
                              for p in range(self.nparts))
                  if f is not None]
        if not frames:
            return None
        return pd.concat(frames, ignore_index=True)

    def merge(self, partial_pdf, merge_fn):
        """Pure per-partition merge of one trigger's partial table:
        returns (merged full frame, touched partition ids) and writes
        NOTHING — the caller persists the frame through the state
        store, commits, then calls `adopt` with the touched set."""
        import pandas as pd
        pids = self._part_ids(partial_pdf)
        touched = sorted(int(p) for p in np.unique(pids))
        frames = []
        for pid in range(self.nparts):
            part = self._read_part(pid)
            if pid in touched:
                part_partial = partial_pdf[pids == pid] \
                    .reset_index(drop=True)
                part = merge_fn(part, part_partial)
            if part is not None and len(part):
                frames.append(part)
        if not frames:
            return None, touched
        return pd.concat(frames, ignore_index=True), touched

    def adopt(self, full_pdf, touched=None) -> None:
        """Move the touched partitions to the adopted (committed)
        frame; `touched=None` rewrites everything (reset). Called only
        after the commit-log write."""
        import pandas as pd
        if touched is None:
            self.reset(full_pdf)
            return
        if full_pdf is None:
            full_pdf = pd.DataFrame(columns=self.key_cols)
        pids = self._part_ids(full_pdf) if len(full_pdf) else None
        for pid in sorted(set(int(p) for p in touched)):
            part = full_pdf.iloc[0:0] if pids is None \
                else full_pdf[pids == pid]
            self._write_part(pid, part.reset_index(drop=True))

"""ExtractPythonUDFs: cut the jitted plan at Python UDF call sites.

The reference pulls PythonUDF expressions out of projections/filters
into BatchEvalPythonExec / ArrowEvalPythonExec stages that stream Arrow
batches to worker processes (`ExtractPythonUDFs.scala`,
`ArrowEvalPythonExec.scala:1`). Here the executor materializes the UDF
node's child subtree (one stage), evaluates the functions host-side over
the compacted Arrow table, and splices the results back as an InputExec
with appended ``__udf_i`` columns — the surrounding plan stays jitted.
"""

from __future__ import annotations

import copy
import decimal as _decimal
from typing import List, Optional

import numpy as np
import pyarrow as pa

from .. import types as T
from ..columnar import Batch
from ..expr import Alias, ColumnRef
from ..plan import physical as P
from ..udf import PythonUDF, evaluate_udf, result_to_arrow

EPOCH = np.datetime64("1970-01-01", "D")


def _collect_udfs(e, out: List[PythonUDF]):
    """Collect INNERMOST-first extractable call sites: a UDF whose args
    contain another UDF waits for the next extraction pass (its args
    must resolve to already-spliced ``__udf_i`` columns first)."""
    if isinstance(e, PythonUDF):
        inner: List[PythonUDF] = []
        for c in e.children:
            _collect_udfs(c, inner)
        out.extend(inner if inner else [e])
        return
    for c in e.children:
        _collect_udfs(c, out)


def node_udfs(node: P.PhysicalPlan) -> List[PythonUDF]:
    out: List[PythonUDF] = []
    if isinstance(node, P.ProjectExec):
        for e in node.exprs:
            _collect_udfs(e, out)
    elif isinstance(node, P.FilterExec):
        _collect_udfs(node.condition, out)
    elif isinstance(node, P.HashAggregateExec) and node.mode != "final":
        # UDFs in group keys or aggregate arguments (group_by(udf(x)),
        # sum(udf(x)) — incl. projections the optimizer collapsed in).
        # FINAL-mode aggregates merge accumulator columns and never
        # evaluate their function children, so they are left alone.
        for g in node.group_exprs:
            _collect_udfs(g, out)
        for a in node.agg_exprs:
            for c in a.func.children:
                _collect_udfs(c, out)
    return out


def plan_has_udfs(root: P.PhysicalPlan) -> bool:
    if node_udfs(root):
        return True
    return any(plan_has_udfs(c) for c in root.children)


def _vec_to_host(vec, n_rows: int):
    """Device Vec -> (python-friendly host array, validity|None) over a
    fully-live (compacted) batch."""
    import jax
    if vec.validity is not None:
        data, valid = jax.device_get((vec.data, vec.validity))
        valid = np.asarray(valid[:n_rows])
    else:
        data, valid = jax.device_get(vec.data), None
    data = np.asarray(data[:n_rows])
    if vec.dictionary is not None:
        values = np.asarray(vec.dictionary.to_pandas(), dtype=object)
        codes = np.clip(data, 0, len(values) - 1)
        data = values[codes] if len(values) else \
            np.full(n_rows, None, dtype=object)
    elif isinstance(vec.dtype, T.DateType):
        data = (EPOCH + data.astype("timedelta64[D]")).astype(object)
    elif isinstance(vec.dtype, T.TimestampType):
        data = data.astype("datetime64[us]").astype(object)
    elif isinstance(vec.dtype, T.DecimalType):
        q = _decimal.Decimal(1).scaleb(-vec.dtype.scale)
        data = np.array([_decimal.Decimal(int(x)) * q for x in data],
                        dtype=object)
    return data, valid


def _eval_udfs_host(udfs: List[PythonUDF], batch: Batch,
                    table: pa.Table, base: int) -> pa.Table:
    """Append one ``__udf_i`` column per call site to the host table."""
    n = table.num_rows
    for i, u in enumerate(udfs, start=base):
        arg_arrays, arg_valids = [], []
        for a in u.children:
            vec = a.eval(batch)  # eager device eval of the arg exprs
            data, valid = _vec_to_host(vec, n)
            arg_arrays.append(data)
            arg_valids.append(valid)
        values, valid = evaluate_udf(u, arg_arrays, arg_valids, n)
        table = table.append_column(f"__udf_{i}", result_to_arrow(
            u, values, valid))
    return table


def _rewrite(e, udfs: List[PythonUDF], base: int, top_level: bool):
    """Replace PythonUDF call sites with refs to their ``__udf_i``
    columns (identity-matched: the same call site object evaluates
    once)."""
    for i, u in enumerate(udfs, start=base):
        if e is u:
            ref = ColumnRef(f"__udf_{i}")
            # a bare top-level UDF projects under its pretty name
            return Alias(ref, e.name()) if top_level else ref
    return e.map_children(lambda c: _rewrite(c, udfs, base, False))


def _agg_rewrite(a, udfs: List[PythonUDF], base: int):
    na = copy.copy(a)
    na.func = a.func.with_args(
        [_rewrite(c, udfs, base, False) for c in a.func.children])
    return na


def extract_python_udfs(root: P.PhysicalPlan, conf) -> P.PhysicalPlan:
    """Bottom-up: materialize each UDF-bearing node's child, evaluate
    the UDFs on host, splice an InputExec (child cols + __udf cols),
    and rewrite the node's expressions over it."""
    new_children = tuple(extract_python_udfs(c, conf)
                         for c in root.children)
    if new_children != root.children:
        root = copy.copy(root)
        root.children = new_children
    from .streaming_agg import _materialize_subtree
    node = root
    # nested calls (udf(udf(x))) extract one layer per iteration
    for _depth in range(16):
        udfs = node_udfs(node)
        if not udfs:
            return node
        child = node.children[0]
        b = _materialize_subtree(child, conf)
        table = b.to_arrow()                      # compact live rows
        cb = Batch.from_arrow(table)              # fully-live device batch
        base = sum(1 for n_ in table.column_names
                   if n_.startswith("__udf_"))
        table = _eval_udfs_host(udfs, cb, table, base)
        nb = Batch.from_arrow(table)
        inp = P.InputExec(nb, nb.schema(), label="python_udf")
        node = copy.copy(node)
        node.children = (inp,)
        if isinstance(node, P.ProjectExec):
            node.exprs = tuple(_rewrite(e, udfs, base, True)
                               for e in node.exprs)
        elif isinstance(node, P.FilterExec):
            node.condition = _rewrite(node.condition, udfs, base, False)
        else:
            node.group_exprs = tuple(_rewrite(g, udfs, base, True)
                                     for g in node.group_exprs)
            node.agg_exprs = tuple(
                _agg_rewrite(a, udfs, base) for a in node.agg_exprs)
    raise RuntimeError("python UDF nesting did not resolve in 16 passes")

"""ExtractPythonUDFs: cut the jitted plan at Python UDF call sites.

The reference pulls PythonUDF expressions out of projections/filters
into BatchEvalPythonExec / ArrowEvalPythonExec stages that stream Arrow
batches to worker processes (`ExtractPythonUDFs.scala`,
`ArrowEvalPythonExec.scala:1`). Here the executor materializes the UDF
node's child subtree (one stage), evaluates the functions host-side over
the compacted Arrow table, and splices the results back as an InputExec
with appended ``__udf_i`` columns — the surrounding plan stays jitted.
"""

from __future__ import annotations

import copy
import decimal as _decimal
import time
from typing import List, Optional

import numpy as np
import pyarrow as pa

from .. import types as T
from ..columnar import Batch
from ..expr import Alias, ColumnRef
from ..plan import physical as P
from ..udf import PythonUDF, evaluate_udf, result_to_arrow

EPOCH = np.datetime64("1970-01-01", "D")

UDF_MODE_KEY = "spark_tpu.sql.udf.mode"
UDF_BATCH_KEY = "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"
UDF_TIMEOUT_KEY = "spark_tpu.sql.udf.batchTimeoutMs"
UDF_MAX_WORKERS_KEY = "spark_tpu.sql.udf.pool.maxWorkers"
UDF_IDLE_KEY = "spark_tpu.sql.udf.pool.idleTimeoutMs"


def _collect_udfs(e, out: List[PythonUDF]):
    """Collect INNERMOST-first extractable call sites: a UDF whose args
    contain another UDF waits for the next extraction pass (its args
    must resolve to already-spliced ``__udf_i`` columns first)."""
    if isinstance(e, PythonUDF):
        inner: List[PythonUDF] = []
        for c in e.children:
            _collect_udfs(c, inner)
        out.extend(inner if inner else [e])
        return
    for c in e.children:
        _collect_udfs(c, out)


def node_udfs(node: P.PhysicalPlan) -> List[PythonUDF]:
    out: List[PythonUDF] = []
    if isinstance(node, P.ProjectExec):
        for e in node.exprs:
            _collect_udfs(e, out)
    elif isinstance(node, P.FilterExec):
        _collect_udfs(node.condition, out)
    elif isinstance(node, P.HashAggregateExec) and node.mode != "final":
        # UDFs in group keys or aggregate arguments (group_by(udf(x)),
        # sum(udf(x)) — incl. projections the optimizer collapsed in).
        # FINAL-mode aggregates merge accumulator columns and never
        # evaluate their function children, so they are left alone.
        for g in node.group_exprs:
            _collect_udfs(g, out)
        for a in node.agg_exprs:
            for c in a.func.children:
                _collect_udfs(c, out)
    return out


def plan_has_udfs(root: P.PhysicalPlan) -> bool:
    if node_udfs(root):
        return True
    return any(plan_has_udfs(c) for c in root.children)


def _vec_to_host(vec, n_rows: int):
    """Device Vec -> (python-friendly host array, validity|None) over a
    fully-live (compacted) batch."""
    import jax
    if vec.validity is not None:
        data, valid = jax.device_get((vec.data, vec.validity))
        valid = np.asarray(valid[:n_rows])
    else:
        data, valid = jax.device_get(vec.data), None
    data = np.asarray(data[:n_rows])
    if vec.dictionary is not None:
        values = np.asarray(vec.dictionary.to_pandas(), dtype=object)
        codes = np.clip(data, 0, len(values) - 1)
        data = values[codes] if len(values) else \
            np.full(n_rows, None, dtype=object)
    elif isinstance(vec.dtype, T.DateType):
        data = (EPOCH + data.astype("timedelta64[D]")).astype(object)
    elif isinstance(vec.dtype, T.TimestampType):
        data = data.astype("datetime64[us]").astype(object)
    elif isinstance(vec.dtype, T.DecimalType):
        q = _decimal.Decimal(1).scaleb(-vec.dtype.scale)
        data = np.array([_decimal.Decimal(int(x)) * q for x in data],
                        dtype=object)
    return data, valid


def _eval_udfs_host(udfs: List[PythonUDF], batch: Batch,
                    table: pa.Table, base: int) -> pa.Table:
    """Append one ``__udf_i`` column per call site to the host table."""
    n = table.num_rows
    for i, u in enumerate(udfs, start=base):
        arg_arrays, arg_valids = [], []
        for a in u.children:
            vec = a.eval(batch)  # eager device eval of the arg exprs
            data, valid = _vec_to_host(vec, n)
            arg_arrays.append(data)
            arg_valids.append(valid)
        values, valid = evaluate_udf(u, arg_arrays, arg_valids, n)
        table = table.append_column(f"__udf_{i}", result_to_arrow(
            u, values, valid))
    return table


def _rt_name(rt: T.DataType) -> str:
    """Return-type NAME for the wire: the worker child never imports
    spark_tpu, so type objects cannot cross the pipe."""
    if isinstance(rt, T.StringType):
        return "string"
    if isinstance(rt, T.DateType):
        return "date"
    if isinstance(rt, T.LongType):
        return "long"
    if isinstance(rt, T.IntegerType):
        return "int"
    if isinstance(rt, T.DoubleType):
        return "double"
    if isinstance(rt, T.FloatType):
        return "float"
    if isinstance(rt, T.BooleanType):
        return "boolean"
    raise TypeError(f"UDF return type {rt!r} has no worker-lane name")


def _host_to_arrow(data, valid, n: int) -> pa.Array:
    """(host array, validity|None) from `_vec_to_host` -> one Arrow arg
    column for the worker. Object arrays (strings, dates, timestamps,
    decimals, dictionary-decoded) go through inference with NULLs
    substituted at invalid slots; numeric arrays keep their dtype with
    the validity as a mask — the worker's `_column_to_args` inverts
    both exactly, so both lanes feed the user function identical
    values."""
    if data.dtype == object:
        if valid is None:
            vals = list(data)
        else:
            vals = [data[i] if valid[i] else None for i in range(n)]
        return pa.array(vals)
    if valid is None:
        return pa.array(data)
    return pa.array(data, mask=~np.asarray(valid, dtype=bool))


def session_pool(session, conf):
    """The session's shared UdfWorkerPool (created in Session.__init__
    so lockwatch can wrap its cv at install time), with its bounds
    refreshed from conf — workers are reused across queries."""
    pool = session._udf_pool
    pool.max_workers = max(1, int(conf.get(UDF_MAX_WORKERS_KEY)))
    pool.idle_timeout_ms = float(conf.get(UDF_IDLE_KEY))
    return pool


def _note_udf_summary(qe, mode: str, batches: int, rows: int,
                      exec_ms: float, restarts: int, max_rec: int) -> None:
    """Accumulate the query's event-log `udf` record (one per query,
    summed across UDF nodes and nesting passes)."""
    if qe is None:
        return
    s = getattr(qe, "udf_summary", None)
    if not s:
        s = {"mode": mode, "batches": 0, "rows": 0, "exec_ms": 0.0,
             "worker_restarts": 0, "max_records_per_batch": int(max_rec)}
    s["batches"] += int(batches)
    s["rows"] += int(rows)
    s["exec_ms"] = round(s["exec_ms"] + float(exec_ms), 3)
    s["worker_restarts"] += int(restarts)
    qe.udf_summary = s


def _eval_udfs_worker(udfs: List[PythonUDF], batch: Batch,
                      table: pa.Table, base: int, conf, qe) -> pa.Table:
    """The out-of-process lane (`spark_tpu.sql.udf.mode=worker`): arg
    expressions still evaluate on device over the whole batch (exactly
    like `_eval_udfs_host`, so results stay byte-identical), but the
    user function runs in pooled subprocess workers, fed Arrow slices
    of `udf.arrow.maxRecordsPerBatch` rows. Each slice is one
    ChunkRetrier chunk at the `udf_batch` fault site: a worker that
    dies (UdfWorkerLost, TRANSIENT) or wedges past `udf.batchTimeoutMs`
    (StageTimeoutError, TIMEOUT) is killed and ONLY the in-flight
    batch replays on a fresh worker (`rec_chunks_replayed`). The
    lifecycle token is checked between batches AND every ~50ms during
    one (the eval poll), and cancel/deadline kills the in-flight
    worker + shuts the pool down — no child survives a cancelled
    query."""
    import cloudpickle
    from ..testing import faults
    from ..udf_worker import UdfError
    from ..udf_worker import protocol
    from ..udf_worker.pool import UdfWorkerLost
    from . import lifecycle
    from .failures import StageTimeoutError
    from .recovery import ChunkRetrier

    n = table.num_rows
    session = qe.session
    metrics = session.metrics
    arg_cols, names, spec_udfs = [], [], []
    for i, u in enumerate(udfs):
        for j, a in enumerate(u.children):
            vec = a.eval(batch)  # eager device eval, same as in-process
            data, valid = _vec_to_host(vec, n)
            arg_cols.append(_host_to_arrow(data, valid, n))
            names.append(f"u{i}_a{j}")
        spec_udfs.append({"fn": cloudpickle.dumps(u.fn),
                          "rt": _rt_name(u.return_type),
                          "vectorized": bool(u.vectorized),
                          "name": u.udf_name,
                          "n_args": len(u.children)})
    args_table = (pa.Table.from_arrays(arg_cols, names=names)
                  if arg_cols else None)

    max_rec = max(1, int(conf.get(UDF_BATCH_KEY)))
    timeout_ms = float(conf.get(UDF_TIMEOUT_KEY))
    timeout_s = timeout_ms / 1e3 if timeout_ms > 0 else None
    pool = session_pool(session, conf)
    retrier = ChunkRetrier(conf, recovery=getattr(qe, "_recovery", None),
                           site="udf_batch")
    held = [None]       # the one worker this query thread holds
    stats = {"batches": 0, "rows": 0, "exec_ms": 0.0, "restarts": 0,
             "had_worker": False}

    def _kill_held():
        h = held[0]
        if h is not None:
            held[0] = None
            pool.discard(h)

    def _poll_cancel():
        tok = lifecycle.current_token()
        if tok is not None and (tok.cancelled or tok.expired()):
            # kill BEFORE raising: the structured cancel error must
            # not leave a child running mid-batch
            _kill_held()
            tok.check("udf_batch")

    def _make_step(ci: int, start: int, ln: int):
        def step() -> pa.Table:
            # chaos seam fires INSIDE the step (ChunkRetrier's
            # udf_batch branch defers to here) so a `fatal` rule can
            # model SIGKILL-mid-batch: kill the in-flight worker for
            # real, then surface as UdfWorkerLost (UNAVAILABLE ->
            # TRANSIENT) — exactly this batch replays on a fresh
            # worker, which is the acceptance contract
            try:
                faults.fire("udf_batch")
            except faults.FaultInjected as fe:
                if fe.fault == "fatal":
                    pid = held[0].pid if held[0] is not None else -1
                    _kill_held()
                    raise UdfWorkerLost(
                        pid, "injected SIGKILL (udf_batch:fatal)") from fe
                raise
            _poll_cancel()
            if held[0] is not None and not held[0].alive():
                _kill_held()
            if held[0] is None:
                held[0] = pool.checkout()
                if stats["had_worker"]:
                    stats["restarts"] += 1
                    metrics.counter("udf_worker_restarts").inc()
                stats["had_worker"] = True
            h = held[0]
            sl = (args_table.slice(start, ln) if args_table is not None
                  else pa.Table.from_arrays([], names=[]))
            payload = protocol.encode_eval(
                {"kind": "batch", "base": base, "udfs": spec_udfs,
                 "n_rows": ln}, sl)
            t0 = time.perf_counter()
            try:
                ftype, pl = h.eval(payload, timeout_s, _poll_cancel)
            except (UdfWorkerLost, StageTimeoutError):
                # dead or wedged: kill + release the slot so the
                # replay (and concurrent queries) get a fresh worker
                _kill_held()
                raise
            t1 = time.perf_counter()
            if ftype == protocol.FRAME_ERROR:
                err = protocol.decode_error(pl)
                raise UdfError(", ".join(u["name"] for u in spec_udfs),
                               err["etype"], err["message"],
                               err["traceback"])
            out = protocol.ipc_to_table(pl)
            if out.num_rows != ln:
                raise protocol.ProtocolError(
                    f"worker returned {out.num_rows} rows for a "
                    f"{ln}-row batch")
            stats["batches"] += 1
            stats["rows"] += ln
            stats["exec_ms"] += (t1 - t0) * 1e3
            metrics.counter("udf_batches").inc()
            metrics.counter("udf_rows").inc(ln)
            metrics.counter("udf_exec_ms").inc(int((t1 - t0) * 1e3))
            qe.spans.record("udf_batch", t0, t1, chunk=ci, rows=ln)
            return out
        return step

    starts = list(range(0, n, max_rec)) or [0]
    result_chunks: List[pa.Table] = []
    try:
        for ci, start in enumerate(starts):
            ln = min(max_rec, n - start) if n else 0
            result_chunks.append(
                retrier.run(_make_step(ci, start, ln), chunk=ci))
    except (lifecycle.QueryCancelledError, lifecycle.QueryDeadlineError):
        # the no-orphan contract: cancel/deadline kills the in-flight
        # worker AND the pool's idle ones — zero children survive a
        # DELETE /queries/<id> landing mid-UDF
        _kill_held()
        pool.shutdown()
        raise
    finally:
        h = held[0]
        if h is not None:
            held[0] = None
            if h.alive():
                pool.checkin(h)   # reuse across batches AND queries
            else:
                pool.discard(h)

    combined = pa.concat_tables(result_chunks)
    for i in range(base, base + len(udfs)):
        name = f"__udf_{i}"
        table = table.append_column(name, combined.column(name))
    _note_udf_summary(qe, "worker", stats["batches"], stats["rows"],
                      stats["exec_ms"], stats["restarts"], max_rec)
    return table


def eval_grouped_map_worker(session, fn, groups, field_names):
    """Grouped-map pandas UDF through the worker pool: one EVAL frame
    per key group (`FlatMapGroupsInPandasExec` over the same pipe
    protocol as the scalar lane). A worker that dies or wedges past
    `udf.batchTimeoutMs` mid-group is killed and only that group
    replays once on a fresh worker; a user exception surfaces as a
    structured UdfError carrying the worker traceback. Returns one
    result frame per group, already projected to `field_names`."""
    import cloudpickle
    from ..udf_worker import UdfError
    from ..udf_worker import protocol
    from ..udf_worker.pool import UdfWorkerLost
    from . import lifecycle
    from .failures import StageTimeoutError

    conf = session.conf
    pool = session_pool(session, conf)
    metrics = session.metrics
    timeout_ms = float(conf.get(UDF_TIMEOUT_KEY))
    timeout_s = timeout_ms / 1e3 if timeout_ms > 0 else None
    spec = {"kind": "grouped_map", "fn": cloudpickle.dumps(fn),
            "fields": list(field_names)}

    def _poll():
        tok = lifecycle.current_token()
        if tok is not None and (tok.cancelled or tok.expired()):
            tok.check("udf_grouped_map")

    out = []
    for g in groups:
        payload = protocol.encode_eval(
            spec, pa.Table.from_pandas(g, preserve_index=False))
        ftype = pl = None
        t0 = time.perf_counter()
        for attempt in (0, 1):
            h = pool.checkout()
            try:
                ftype, pl = h.eval(payload, timeout_s, _poll)
            except (UdfWorkerLost, StageTimeoutError):
                pool.discard(h)
                metrics.counter("udf_worker_restarts").inc()
                if attempt:
                    raise
                continue
            except BaseException:
                # cancel/deadline (or anything else) mid-group: the
                # in-flight worker's pipe holds a half-read frame —
                # kill it rather than pool a poisoned handle
                pool.discard(h)
                raise
            pool.checkin(h)
            break
        t1 = time.perf_counter()
        if ftype == protocol.FRAME_ERROR:
            err = protocol.decode_error(pl)
            raise UdfError(getattr(fn, "__name__", "grouped_map"),
                           err["etype"], err["message"],
                           err["traceback"])
        res = protocol.ipc_to_table(pl)
        metrics.counter("udf_batches").inc()
        metrics.counter("udf_rows").inc(res.num_rows)
        metrics.counter("udf_exec_ms").inc(int((t1 - t0) * 1e3))
        out.append(res.to_pandas())
    return out


def _rewrite(e, udfs: List[PythonUDF], base: int, top_level: bool):
    """Replace PythonUDF call sites with refs to their ``__udf_i``
    columns (identity-matched: the same call site object evaluates
    once)."""
    for i, u in enumerate(udfs, start=base):
        if e is u:
            ref = ColumnRef(f"__udf_{i}")
            # a bare top-level UDF projects under its pretty name
            return Alias(ref, e.name()) if top_level else ref
    return e.map_children(lambda c: _rewrite(c, udfs, base, False))


def _agg_rewrite(a, udfs: List[PythonUDF], base: int):
    na = copy.copy(a)
    na.func = a.func.with_args(
        [_rewrite(c, udfs, base, False) for c in a.func.children])
    return na


def extract_python_udfs(root: P.PhysicalPlan, conf,
                        qe=None) -> P.PhysicalPlan:
    """Bottom-up: materialize each UDF-bearing node's child, evaluate
    the UDFs (in-process, or through the worker pool when
    `spark_tpu.sql.udf.mode=worker` — `qe` carries the session/pool,
    recovery context, and span recorder), splice an InputExec (child
    cols + __udf cols), and rewrite the node's expressions over it."""
    new_children = tuple(extract_python_udfs(c, conf, qe=qe)
                         for c in root.children)
    if new_children != root.children:
        root = copy.copy(root)
        root.children = new_children
    from .streaming_agg import _materialize_subtree
    node = root
    worker_mode = (str(conf.get(UDF_MODE_KEY) or "inprocess") == "worker"
                   and qe is not None)
    # nested calls (udf(udf(x))) extract one layer per iteration
    for _depth in range(16):
        udfs = node_udfs(node)
        if not udfs:
            return node
        child = node.children[0]
        b = _materialize_subtree(child, conf)
        table = b.to_arrow()                      # compact live rows
        cb = Batch.from_arrow(table)              # fully-live device batch
        base = sum(1 for n_ in table.column_names
                   if n_.startswith("__udf_"))
        if worker_mode:
            table = _eval_udfs_worker(udfs, cb, table, base, conf, qe)
        else:
            t0 = time.perf_counter()
            table = _eval_udfs_host(udfs, cb, table, base)
            _note_udf_summary(
                qe, "inprocess", batches=1, rows=table.num_rows,
                exec_ms=(time.perf_counter() - t0) * 1e3, restarts=0,
                max_rec=int(conf.get(UDF_BATCH_KEY)))
        nb = Batch.from_arrow(table)
        inp = P.InputExec(nb, nb.schema(), label="python_udf")
        node = copy.copy(node)
        node.children = (inp,)
        if isinstance(node, P.ProjectExec):
            node.exprs = tuple(_rewrite(e, udfs, base, True)
                               for e in node.exprs)
        elif isinstance(node, P.FilterExec):
            node.condition = _rewrite(node.condition, udfs, base, False)
        else:
            node.group_exprs = tuple(_rewrite(g, udfs, base, True)
                                     for g in node.group_exprs)
            node.agg_exprs = tuple(
                _agg_rewrite(a, udfs, base) for a in node.agg_exprs)
    raise RuntimeError("python UDF nesting did not resolve in 16 passes")

"""Hash build/probe equi-join kernel: open-addressing table over the
sorted build side.

The sort kernel (execution/join.py) binary-searches each probe key with
``jnp.searchsorted(..., method='sort')`` — correct and fast for small
probes, but each searchsorted call SORTS the probe side (two calls per
join), so on the join-bound TPC-H shapes (Q3/Q5: 6M-60M probe rows
against sub-million builds) the probe-side sorts dominate the profile.
This module is the ``BytesToBytesMap.java`` seat retold for XLA: build
a power-of-two open-addressing table (linear probing, murmur-mixed
int64 keys) over the build side's DISTINCT keys as device arrays, then
probe with a fixed-bound vectorized loop — O(expected cluster length)
small-table gathers per probe row instead of O(P log P) sort work.

Design notes:

- The build side is still sorted once (``join.build_sorted`` — the
  build is the small side, and sorting groups duplicate keys into
  runs). The table stores, per distinct key, the POSITION of its run
  start in the sorted array; run lengths come from a per-run count.
  The probe therefore returns the exact ``(lo, cnt)`` pair the sort
  kernel's ``match_ranges`` returns, so the many-to-many prefix-sum
  expansion (``join.expand``), the unique-build FK->PK fast path and
  every downstream gather are SHARED between kernels and the two
  paths produce byte-identical output (same rows, same order).
- Table capacity is a static power of two derived from the (already
  bucketed) build capacity and ``join.hashLoadFactor``, clamped by
  ``join.hashMaxTableSlots`` — stage keys stay stable per capacity
  bucket. A clamp that would push the load factor past
  ``_FALLBACK_LOAD_FACTOR`` falls back to the sort kernel at trace
  time (the analyzer's JOIN_HASH_TABLE_PRESSURE finding predicts
  this).
- Inserts claim vacant slots with a scatter-min among the round's
  contenders (occupied slots are never stolen, preserving the linear-
  probing invariant the probe's early-exit relies on); both loops are
  ``lax.while_loop``s bounded by ``join.hashMaxProbe`` with an
  all-done early exit. A build whose longest cluster exceeds the
  bound raises the ``join_hashsat_<tag>`` flag and the executor's AQE
  loop re-jits that join on the sort kernel — correctness never
  depends on the probe bound.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..expr import Vec

KERNEL_MODE_KEY = "spark_tpu.sql.join.kernelMode"
LOAD_FACTOR_KEY = "spark_tpu.sql.join.hashLoadFactor"
MAX_PROBE_KEY = "spark_tpu.sql.join.hashMaxProbe"
MAX_SLOTS_KEY = "spark_tpu.sql.join.hashMaxTableSlots"
MIN_PROBE_ROWS_KEY = "spark_tpu.sql.join.hashMinProbeRows"
PROBE_BUILD_RATIO_KEY = "spark_tpu.sql.join.hashProbeBuildRatio"

#: effective load factor past which a (maxTableSlots-clamped) table
#: degrades to long clusters: fall back to the sort kernel instead
_FALLBACK_LOAD_FACTOR = 0.7

#: bytes per table slot (int32 position) + the per-position run-count
#: array the probe gathers through — the analyzer's HBM estimate
SLOT_BYTES = 16


def _want_slots(build_cap: int, conf) -> int:
    """Unclamped table capacity: smallest power of two holding
    `build_cap` distinct keys at `hashLoadFactor`."""
    load = float(conf.get(LOAD_FACTOR_KEY))
    want = max(int(np.ceil(max(int(build_cap), 1) / load)), 16)
    return 1 << int(np.ceil(np.log2(want)))


def table_slots(build_cap: int, conf) -> int:
    """Static table capacity: `_want_slots` clamped by
    `hashMaxTableSlots`. `build_cap` is already bucketed (batch
    capacities always are), so the result is stable per capacity
    bucket."""
    # floor the clamp to a power of two: slot indexing masks with
    # `& (slots - 1)`, so a non-power-of-two conf value would leave
    # every slot above the highest mask bit unreachable
    max_slots = int(conf.get(MAX_SLOTS_KEY))
    return min(_want_slots(build_cap, conf),
               1 << (max_slots.bit_length() - 1))


def kernel_choice(conf, probe_cap: int, build_cap: int,
                  hash_fallback=None) -> Tuple[str, str]:
    """('hash'|'sort', reason) for one join instance, decided at trace
    time from static capacities — the ONE decision procedure, shared
    with the analyzer's JOIN_HASH_TABLE_PRESSURE prediction so the two
    can't drift. `hash_fallback` is the per-join AQE state: False means
    a previous attempt saturated the table (or the planner persisted
    that outcome) — stay on sort.

    Reasons: 'pinned' (AQE saturation pin), 'forced' (kernelMode said
    so), 'small-probe'/'ratio' (auto heuristics keep sort), 'clamp'
    (the mode WANTED hash but the maxTableSlots clamp pushes the load
    factor past the fallback bound — the degraded case the analyzer
    reports), 'auto' (auto picked hash)."""
    if hash_fallback is False:
        return "sort", "pinned"
    mode = str(conf.get(KERNEL_MODE_KEY))
    if mode == "sort":
        return "sort", "forced"
    if mode == "auto":
        # the table build amortizes only over large, probe-heavy joins
        if int(probe_cap) < int(conf.get(MIN_PROBE_ROWS_KEY)):
            return "sort", "small-probe"
        if int(probe_cap) < float(conf.get(PROBE_BUILD_RATIO_KEY)) \
                * int(build_cap):
            return "sort", "ratio"
    slots = table_slots(build_cap, conf)
    # the fallback bound applies only when the maxTableSlots clamp
    # actually reduced the table: an UNCLAMPED table honors the
    # configured hashLoadFactor by construction (power-of-two rounding
    # only lowers the effective load), and a user-chosen loadFactor in
    # (0.7, 0.9] is their call — saturation + the AQE sort pin still
    # backstop pathological clusters
    if slots < _want_slots(build_cap, conf) \
            and int(build_cap) > _FALLBACK_LOAD_FACTOR * slots:
        return "sort", "clamp"  # maxTableSlots: load factor too high
    return "hash", ("forced" if mode == "hash" else "auto")


def resolve_kernel(conf, probe_cap: int, build_cap: int,
                   hash_fallback=None) -> str:
    return kernel_choice(conf, probe_cap, build_cap, hash_fallback)[0]


#: splitmix64-style finalizer seed (shared by build and probe — the
#: ONE requirement; value mirrors murmur3's c1 for no deeper reason)
_HASH_SEED = 0xCC9E2D51


def _hash_keys(keys, hash_dtype=None) -> jnp.ndarray:
    """Murmur-mixed int64 hash of a key column. Floats hash by BIT
    PATTERN (truncation to int would fold [0,1) onto one slot), with
    +-0.0 and NaN payloads canonicalized so keys the join treats as
    equal hash equal; collisions only cost probe steps — the table
    compares true key values.

    `hash_dtype` is the PROMOTED common dtype of the two key sides
    (jnp.promote_types): build and probe must hash under one dtype, or
    numerically equal mixed-precision keys (float32 probe vs float64
    build) hash different bit patterns and every match is silently
    missed. The cast mirrors the numeric promotion `==` applies in the
    probe's hit test and searchsorted applies in the sort kernel."""
    from ..sketch import _mix64
    from .join import canon_key_data
    if hash_dtype is not None and keys.dtype != hash_dtype:
        keys = keys.astype(hash_dtype)
    if jnp.issubdtype(keys.dtype, jnp.floating):
        keys = canon_key_data(keys)
        width = keys.dtype.itemsize * 8
        keys = jax.lax.bitcast_convert_type(
            keys, jnp.int32 if width == 32 else jnp.int64)
    return _mix64(keys.astype(jnp.int64), _HASH_SEED).astype(jnp.int64)


def _keys_equal(a, b):
    """Join-key equality, matching the sort kernel's searchsorted TOTAL
    order: NaN groups with NaN (the reference joins NaN keys equal,
    and `match_ranges` already does via sort order); +-0.0 compare
    equal under IEEE `==` as they do under sorting."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    return eq


def build_table(keys_s, valid_s, slots: int, max_probe: int,
                hash_dtype=None) -> Tuple:
    """Insert each distinct valid build key into the open table.

    `keys_s`/`valid_s` come from ``join.build_sorted`` (valid prefix,
    invalid slots overwritten with a +max sentinel). Returns
    ``(t_pos, cnt_all, saturated)``:

      t_pos[s]    sorted-array position of the run START of the key
                  stored in slot s, or `cap` (empty)
      cnt_all[p]  number of VALID rows in position p's key run (valid
                  rows of a run are contiguous from its start, so
                  [start, start+cnt) are exactly the matches)
      saturated   traced bool: some key failed to claim a slot within
                  `max_probe` steps — the caller flags it and the AQE
                  loop re-jits on the sort kernel
    """
    cap = keys_s.shape[0]
    i32 = jnp.int32
    pos = jnp.arange(cap, dtype=i32)
    prev_same = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_), _keys_equal(keys_s[1:], keys_s[:-1])])
    is_start = (~prev_same) & valid_s
    # per-run valid-row counts: one scatter-add over the (small) build
    run_id = jnp.cumsum(is_start.astype(i32)) - 1
    counts = jnp.zeros((cap,), i32).at[
        jnp.where(valid_s, run_id, cap)].add(1, mode="drop")
    cnt_all = jnp.take(counts, jnp.clip(run_id, 0, cap - 1))

    h = (_hash_keys(keys_s, hash_dtype) & (slots - 1)).astype(i32)
    t_pos0 = jnp.full((slots,), cap, i32)

    def cond(state):
        d, _t, claimed = state
        return (d < max_probe) & ~jnp.all(claimed | ~is_start)

    def body(state):
        d, t_pos, claimed = state
        want = is_start & ~claimed
        s = (h + d) & (slots - 1)
        # min contender per slot this round, merged only into VACANT
        # slots: an occupied slot is never stolen, so the linear-
        # probing invariant (no vacancy between h(K) and K's slot)
        # holds and the probe may stop at the first vacancy
        scratch = jnp.full((slots,), cap, i32).at[
            jnp.where(want, s, slots)].min(pos, mode="drop")
        vacant = t_pos == cap
        t_new = jnp.where(vacant & (scratch < cap), scratch, t_pos)
        claimed = claimed | (want & (jnp.take(t_new, s) == pos))
        return d + 1, t_new, claimed

    _d, t_pos, claimed = jax.lax.while_loop(
        cond, body, (jnp.zeros((), i32), t_pos0,
                     jnp.zeros((cap,), jnp.bool_)))
    saturated = jnp.any(is_start & ~claimed)
    return t_pos, cnt_all, saturated


def probe_table(t_pos, cnt_all, keys_s, probe_key: Vec, probe_sel,
                slots: int, max_probe: int, hash_dtype=None) -> Tuple:
    """Vectorized fixed-bound probe: returns the sort kernel's
    ``(lo, cnt)`` contract (``join.match_ranges``) — build rows
    [lo, lo+cnt) in sorted order match; cnt is 0 for unmatched,
    NULL-key or unselected probe rows.

    Every inserted key sits within `max_probe` steps of its home slot
    with no vacancy before it, so a probe that hits a vacant slot (or
    exhausts the bound against a table built without saturation) has
    PROVEN a miss — no false negatives."""
    cap = keys_s.shape[0]
    i32 = jnp.int32
    pk = probe_key.data  # raw values: IEEE == already treats +-0 equal
    ph = (_hash_keys(probe_key.data, hash_dtype) & (slots - 1)).astype(i32)
    n = pk.shape[0]
    lo0 = jnp.zeros((n,), i32)
    cnt0 = jnp.zeros((n,), i32)
    done0 = jnp.zeros((n,), jnp.bool_)

    def cond(state):
        d, _lo, _cnt, done = state
        return (d < max_probe) & ~jnp.all(done)

    def body(state):
        d, lo, cnt, done = state
        s = (ph + d) & (slots - 1)
        tp = jnp.take(t_pos, s)
        occupied = tp < cap
        tpc = jnp.minimum(tp, cap - 1)
        hit = occupied & _keys_equal(jnp.take(keys_s, tpc), pk) & ~done
        lo = jnp.where(hit, tp, lo)
        cnt = jnp.where(hit, jnp.take(cnt_all, tpc), cnt)
        done = done | hit | ~occupied
        return d + 1, lo, cnt, done

    _d, lo, cnt, _done = jax.lax.while_loop(
        cond, body, (jnp.zeros((), i32), lo0, cnt0, done0))
    found = cnt > 0
    if probe_key.validity is not None:
        found = found & probe_key.validity
    if probe_sel is not None:
        found = found & probe_sel
    cnt = jnp.where(found, cnt, 0).astype(i32)
    return lo, cnt

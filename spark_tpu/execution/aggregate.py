"""Group-by aggregation kernels.

Replaces the reference's Tungsten hash aggregation
(`HashAggregateExec.scala:46`, `TungstenAggregationIterator.scala:82`,
`UnsafeFixedWidthAggregationMap.java:39` on `BytesToBytesMap.java`) with
two TPU-native strategies chosen at trace time:

1. **direct**: when every group key has a statically known small integer
   domain (dictionary-encoded strings -> |dict|, `x % c` -> c, bool -> 2,
   byte -> 256), the combined domain is a dense table and aggregation is
   a scatter-add/min/max (segment reduce) — no hash table at all. This is
   the common case for TPC-H-style low-cardinality GROUP BYs and is the
   op the MXU/VPU executes at memory bandwidth.
2. **sort**: general exact fallback — multi-operand `lax.sort` on the key
   columns (the XLA analog of Tungsten's sort-based fallback path), group
   boundaries by adjacent-difference, then `jax.ops.segment_*`.

Both paths consume the declarative accumulator specs of
``expr_agg.AggregateFunction`` and produce a Batch of group keys +
accumulator columns with an `occupied` selection; merge across shards
re-reduces the same accumulators (associative + commutative), which is
what makes the partial/final split and mesh `psum` trees work unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column, bucket_capacity
from ..expr import Expression, Literal, Mod, Vec
from ..expr_agg import AccSpec, AggExpr


def key_domain(expr: Expression, vec: Vec) -> Optional[int]:
    """Statically-known integer key domain, or None (trace-time decision)."""
    if vec.dictionary is not None:
        return len(vec.dictionary)
    if isinstance(vec.dtype, T.BooleanType):
        return 2
    if isinstance(vec.dtype, T.ByteType):
        return 256
    if isinstance(expr, Mod):
        div = expr.children[1]
        while hasattr(div, "child") and div.children:
            div = div.children[0]
        if isinstance(div, Literal) and isinstance(div.value, int) and div.value > 0:
            return int(div.value)
    return None


def _key_index(vec: Vec, domain: int):
    idx = vec.data.astype(jnp.int32)
    if isinstance(vec.dtype, T.BooleanType):
        idx = vec.data.astype(jnp.int32)
    return jnp.clip(idx, 0, domain - 1)


_SEGMENT_REDUCE = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def direct_aggregate(key_vecs: Sequence[Vec], domains: Sequence[int],
                     contribs: List[List], specs: List[List[AccSpec]],
                     sel) -> Tuple[List, List, object]:
    """Dense-domain aggregation. Returns (key_arrays, acc_arrays, occupied)."""
    total = 1
    strides = []
    for d in domains:
        strides.append(total)
        total *= d
    idx = jnp.zeros((), jnp.int32)
    for vec, d, s in zip(key_vecs, domains, strides):
        idx = idx + _key_index(vec, d) * s
    # drop unselected rows via out-of-bounds index
    if sel is not None:
        idx = jnp.where(sel, idx, total)
    occupied_cnt = jnp.zeros((total,), jnp.int32).at[idx].add(
        jnp.ones_like(idx), mode="drop")
    accs = []
    for row_contribs, row_specs in zip(contribs, specs):
        fn_accs = []
        for contrib, spec in zip(row_contribs, row_specs):
            init = jnp.full((total,), spec.neutral)
            if spec.reduce == "sum":
                out = jnp.zeros((total,), spec.np_dtype).at[idx].add(
                    contrib, mode="drop")
            elif spec.reduce == "min":
                out = init.at[idx].min(contrib, mode="drop")
            else:
                out = init.at[idx].max(contrib, mode="drop")
            fn_accs.append(out)
        accs.append(fn_accs)
    # reconstruct key values from the dense index
    out_idx = jnp.arange(total, dtype=jnp.int32)
    key_arrays = []
    rem = out_idx
    for d, s, vec in zip(reversed(domains), reversed(strides), reversed(key_vecs)):
        k = rem // s
        rem = rem - k * s
        key_arrays.append(k.astype(vec.dtype.np_dtype))
    key_arrays.reverse()
    return key_arrays, accs, occupied_cnt > 0


def sort_aggregate(key_vecs: Sequence[Vec],
                   contribs: List[List], specs: List[List[AccSpec]],
                   sel, capacity: int, num_segments: Optional[int] = None
                   ) -> Tuple[List, List, List, object]:
    """General sort-based aggregation.

    Returns (key_arrays, key_validities, acc_arrays, occupied).
    """
    num_segments = num_segments or capacity
    operands = []
    invalid = jnp.zeros((capacity,), jnp.int32) if sel is None else \
        (~sel).astype(jnp.int32)
    operands.append(invalid)
    for vec in key_vecs:
        if vec.validity is not None:
            operands.append((~vec.validity).astype(jnp.int8))
        operands.append(vec.data)
    num_keys = len(operands)
    operands.append(jnp.arange(capacity, dtype=jnp.int32))  # permutation payload
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    inv_sorted = sorted_ops[0].astype(jnp.bool_)
    valid_sorted = ~inv_sorted

    # group starts: first valid row, or any key component differing from prev
    diff = jnp.zeros((capacity,), jnp.bool_)
    for op in sorted_ops[1:num_keys]:
        shifted = jnp.roll(op, 1)
        diff = diff | (op != shifted)
    first = jnp.arange(capacity) == 0
    starts = (first | diff) & valid_sorted
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
    gid = jnp.where(valid_sorted, gid, num_segments)  # OOB -> dropped

    occupied_cnt = jnp.zeros((num_segments,), jnp.int32).at[gid].add(
        jnp.ones_like(gid), mode="drop")

    accs = []
    for row_contribs, row_specs in zip(contribs, specs):
        fn_accs = []
        for contrib, spec in zip(row_contribs, row_specs):
            contrib_sorted = jnp.take(contrib, perm)
            red = _SEGMENT_REDUCE[spec.reduce]
            out = red(contrib_sorted, gid, num_segments=num_segments + 1)[:-1]
            if spec.reduce != "sum":
                neutral = jnp.full((num_segments,), spec.neutral)
                out = jnp.where(occupied_cnt > 0, out, neutral)
            fn_accs.append(out.astype(spec.np_dtype))
        accs.append(fn_accs)

    # scatter first-of-group key values into the output slots
    key_arrays = []
    key_valids = []
    oi = 1
    for vec in key_vecs:
        if vec.validity is not None:
            null_sorted = sorted_ops[oi].astype(jnp.bool_)
            oi += 1
        else:
            null_sorted = None
        data_sorted = sorted_ops[oi]
        oi += 1
        out = jnp.zeros((num_segments,), data_sorted.dtype).at[
            jnp.where(starts, gid, num_segments)].set(data_sorted, mode="drop")
        key_arrays.append(out)
        if null_sorted is not None:
            kv = jnp.ones((num_segments,), jnp.bool_).at[
                jnp.where(starts, gid, num_segments)].set(
                    ~null_sorted, mode="drop")
            key_valids.append(kv)
        else:
            key_valids.append(None)
    return key_arrays, key_valids, accs, occupied_cnt > 0

"""Group-by aggregation kernels.

Replaces the reference's Tungsten hash aggregation
(`HashAggregateExec.scala:46`, `TungstenAggregationIterator.scala:82`,
`UnsafeFixedWidthAggregationMap.java:39` on `BytesToBytesMap.java`) with
two TPU-native strategies chosen at trace time:

1. **direct**: when every group key has a statically known small integer
   domain (dictionary-encoded strings -> |dict|, `x % c` -> c, bool -> 2,
   byte -> 256), the combined domain is a dense table and aggregation is
   a scatter-add/min/max (segment reduce) — no hash table at all. This is
   the common case for TPC-H-style low-cardinality GROUP BYs and is the
   op the MXU/VPU executes at memory bandwidth.
2. **sort**: general exact fallback — multi-operand `lax.sort` on the key
   columns (the XLA analog of Tungsten's sort-based fallback path), group
   boundaries by adjacent-difference, then `jax.ops.segment_*`.

Both paths consume the declarative accumulator specs of
``expr_agg.AggregateFunction`` and produce a Batch of group keys +
accumulator columns with an `occupied` selection; merge across shards
re-reduces the same accumulators (associative + commutative), which is
what makes the partial/final split and mesh `psum` trees work unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column, bucket_capacity
from ..expr import Alias, Expression, Literal, Mod, Pmod, Vec
from ..expr_agg import AccSpec, AggExpr


def key_domain(expr: Expression, vec: Vec) -> Optional[Tuple[int, int]]:
    """Statically-known integer key range as (domain, lo) with
    value in [lo, lo+domain), or None (trace-time decision).

    `lo` matters for signed ranges: truncated `%` yields (-m, m) and BYTE
    is [-128, 128) — a [0, domain) assumption would silently merge
    negative keys into slot 0."""
    while isinstance(expr, Alias):
        expr = expr.child
    if vec.dictionary is not None:
        return len(vec.dictionary), 0
    if isinstance(vec.dtype, T.BooleanType):
        return 2, 0
    if isinstance(vec.dtype, T.ByteType):
        return 256, -128
    if isinstance(expr, Mod):
        div = expr.children[1]
        while hasattr(div, "child") and div.children:
            div = div.children[0]
        if isinstance(div, Literal) and isinstance(div.value, int) and div.value > 0:
            m = int(div.value)
            if isinstance(expr, Pmod):
                return m, 0
            # truncated %: result in (-m, m)
            return 2 * m - 1, -(m - 1)
    return None


def _key_index(vec: Vec, domain: int, lo: int):
    idx = vec.data.astype(jnp.int32) - jnp.int32(lo)
    return jnp.clip(idx, 0, domain - 1)


_SEGMENT_REDUCE = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _sorted_segment_reduce(contrib_sorted, reduce: str, starts_rows,
                           start_pos, end_pos, present):
    """Per-segment reduce over rows SORTED by segment, without a
    colliding scatter (XLA scatter-add into shared slots serializes on
    TPU: ~300ms for 4M rows into 65k segments, measured on Q3).

    integer sum: prefix-sum difference csum[end] - csum[start-1] — int64
    wraps mod 2^64 so the difference is exact. float sum and min/max: a
    SEGMENTED associative scan (reset at `starts_rows` markers) read at
    segment ends — a global-prefix difference would put each segment's
    float error at the ulp of the whole-table running total instead of
    the segment's own magnitude. start_pos/end_pos index each segment's
    first/last sorted row; `present` masks empty segments."""
    is_int = jnp.issubdtype(contrib_sorted.dtype, jnp.integer)
    if reduce == "sum" and is_int:
        csum = jnp.cumsum(contrib_sorted)
        ex = csum - contrib_sorted  # exclusive prefix
        out = jnp.take(csum, end_pos) - jnp.take(ex, start_pos)
        return jnp.where(present, out, jnp.zeros_like(out))
    if reduce == "sum":
        op = jnp.add
    else:
        op = jnp.minimum if reduce == "min" else jnp.maximum

    def combine(a, b):
        va, fa = a
        vb, fb = b
        return (jnp.where(fb, vb, op(va, vb)), fa | fb)

    scanned, _ = jax.lax.associative_scan(
        combine, (contrib_sorted, starts_rows))
    out = jnp.take(scanned, end_pos)
    if reduce == "sum":
        out = jnp.where(present, out, jnp.zeros_like(out))
    return out


def key_spans(nullables: Sequence[bool],
              domains: Sequence[Tuple[int, int]]) -> List[int]:
    """Per-key slot count: the value domain plus one NULL slot for
    SCHEMA-nullable keys (SQL groups NULL keys together). Nullability
    comes from the schema, not a batch's concrete validity — chunked
    execution must keep ONE layout even when some chunks lack nulls."""
    return [d + (1 if nullable else 0)
            for nullable, (d, _lo) in zip(nullables, domains)]


def direct_index(key_vecs: Sequence[Vec], domains: Sequence[Tuple[int, int]],
                 spans: Sequence[int], sel):
    """Combined dense-domain index per row; unselected rows get an
    out-of-bounds index (scatter mode='drop' discards them); NULL key
    values map to the key's dedicated null slot.
    `domains` entries are (domain, lo) pairs from `key_domain`."""
    total = 1
    strides = []
    for span in spans:
        strides.append(total)
        total *= span
    idx = jnp.zeros((), jnp.int32)
    for vec, (d, lo), span, s in zip(key_vecs, domains, spans, strides):
        ki = _key_index(vec, d, lo)
        if vec.validity is not None and span > d:
            ki = jnp.where(vec.validity, ki, jnp.int32(d))  # null slot
        idx = idx + ki * s
    if sel is not None:
        idx = jnp.where(sel, idx, total)
    return idx, total, strides


def direct_init(spans: Sequence[int], specs: List[List[AccSpec]]):
    """Fresh accumulator tables: (occupied_cnt, [[acc,...],...]).
    `spans` are the per-key slot counts incl. null slots (key_spans)."""
    total = int(np.prod(list(spans) or [1]))
    cnt = jnp.zeros((total,), jnp.int64)
    accs = [[jnp.full((total,), spec.neutral) for spec in row]
            for row in specs]
    return cnt, accs


def direct_update(tables, idx, total, contribs: List[List],
                  specs: List[List[AccSpec]], kernel_mode: str = "auto",
                  merge: bool = False,
                  reuse_count: Optional[Tuple[int, int]] = None):
    """Merge one chunk's contributions into carried tables (associative).

    kernel_mode: 'auto' uses the Pallas MXU one-hot matmul kernel on TPU
    (XLA scatter-add with colliding indices is ~100x slower there) and
    plain scatter elsewhere; 'matmul'/'scatter' force a path ('matmul'
    off-TPU runs the kernel in interpret mode, for tests).

    merge=True means the contributions are PARTIAL ACCUMULATORS (a final
    -mode aggregate folding per-shard tables), not raw per-row values:
    AccSpec.width bounds only the raw update, so merge forces full
    64-bit limbs — a partial count easily exceeds 2^8.

    reuse_count=(i, j): the caller promises contribs[i][j] equals the
    selection indicator (a count over a never-null child), so the
    kernel's occupancy row rides that row's sums instead of adding its
    own — the MXU kernel cost is linear in limb rows, and a count-only
    aggregate (post RewriteGroupKeyAggregates) drops from 2 rows to 1.
    Ignored in merge mode (partial counts are not indicators).
    """
    cnt, accs = tables
    if np.ndim(idx) == 0:
        idx = jnp.broadcast_to(idx, contribs[0][0].shape if contribs
                               and contribs[0] else (1,))

    all_sum = all(spec.reduce == "sum" for row in specs for spec in row)
    backend = jax.default_backend()
    use_kernel = (kernel_mode == "matmul"
                  or (kernel_mode == "auto" and backend == "tpu"))
    has_float = any(np.issubdtype(spec.np_dtype, np.floating)
                    for row in specs for spec in row)
    if has_float and total > 512:
        # the VPU masked-reduce float path costs O(domain) per row; the
        # factorized MXU kernel is int-only — scatter instead
        use_kernel = False
    if all_sum and use_kernel and total <= (1 << 20) and \
            (idx.shape[0] >= 128 or kernel_mode == "matmul"):
        from .pallas_groupby import dense_groupby_sums
        reuse = reuse_count if not merge else None
        if reuse is not None:
            int_rows = []
            int_widths = []
        else:
            int_rows = [jnp.ones(idx.shape, jnp.int64)]
            int_widths = [8]  # the occupancy count contributes 0/1
        float_rows = []
        layout = []  # (row_kind, index) per (i, j)
        reuse_pos = None
        for i, (contrib_row, spec_row) in enumerate(zip(contribs, specs)):
            for j, (contrib, spec) in enumerate(zip(contrib_row, spec_row)):
                if np.issubdtype(spec.np_dtype, np.floating):
                    layout.append(("f", len(float_rows)))
                    float_rows.append(contrib)
                else:
                    layout.append(("i", len(int_rows)))
                    if reuse == (i, j):
                        reuse_pos = len(int_rows)
                    int_rows.append(contrib.astype(jnp.int64))
                    int_widths.append(64 if merge else spec.width)
        if reuse is not None and reuse_pos is None:
            # promised row turned out to be a float row: fall back
            int_rows = [jnp.ones(idx.shape, jnp.int64)] + int_rows
            int_widths = [8] + int_widths
            layout = [(k, p + 1) if k == "i" else (k, p)
                      for (k, p) in layout]
            reuse_pos = 0
        int_sums, float_sums = dense_groupby_sums(
            idx, int_rows, float_rows, total,
            interpret=(backend != "tpu"), int_widths=int_widths)
        cnt = cnt + int_sums[reuse_pos if reuse_pos is not None else 0]
        new_accs = []
        k = 0
        for table_row, spec_row in zip(accs, specs):
            new_row = []
            for table, spec in zip(table_row, spec_row):
                kind, pos = layout[k]
                k += 1
                if kind == "f":
                    new_row.append(table + float_sums[pos].astype(spec.np_dtype))
                else:
                    new_row.append(table + int_sums[pos].astype(spec.np_dtype))
            new_accs.append(new_row)
        return cnt, new_accs

    cnt = cnt.at[idx].add(jnp.ones(idx.shape, jnp.int64), mode="drop")
    new_accs = []
    for table_row, contrib_row, spec_row in zip(accs, contribs, specs):
        new_row = []
        for table, contrib, spec in zip(table_row, contrib_row, spec_row):
            if spec.reduce == "sum":
                new_row.append(table.at[idx].add(contrib, mode="drop"))
            elif spec.reduce == "min":
                new_row.append(table.at[idx].min(contrib, mode="drop"))
            else:
                new_row.append(table.at[idx].max(contrib, mode="drop"))
        new_accs.append(new_row)
    return cnt, new_accs


def direct_keys(domains: Sequence[Tuple[int, int]],
                spans: Sequence[int], strides: Sequence[int],
                key_dtypes: Sequence[T.DataType]) -> Tuple[List, List]:
    """Reconstruct key column (values, validities) from the dense domain
    index. A key's null slot (index == domain) decodes to validity False;
    keys without a null slot get validity None."""
    total = int(np.prod(list(spans) or [1]))
    out_idx = jnp.arange(total, dtype=jnp.int32)
    key_arrays = []
    key_valids = []
    rem = out_idx
    for (d, lo), span, s, dt in zip(reversed(list(domains)),
                                    reversed(list(spans)),
                                    reversed(strides),
                                    reversed(list(key_dtypes))):
        k = rem // s
        rem = rem - k * s
        if span > d:  # has a null slot
            key_valids.append(k != d)
            k = jnp.minimum(k, d - 1)
        else:
            key_valids.append(None)
        key_arrays.append((k + jnp.int32(lo)).astype(dt.np_dtype))
    key_arrays.reverse()
    key_valids.reverse()
    return key_arrays, key_valids


def direct_aggregate(key_vecs: Sequence[Vec],
                     domains: Sequence[Tuple[int, int]],
                     spans: Sequence[int],
                     contribs: List[List], specs: List[List[AccSpec]],
                     sel, kernel_mode: str = "auto",
                     merge: bool = False,
                     reuse_count: Optional[Tuple[int, int]] = None
                     ) -> Tuple[List, List, List, object]:
    """One-shot dense-domain aggregation.
    Returns (key_arrays, key_valids, acc_arrays, occupied)."""
    idx, total, strides = direct_index(key_vecs, domains, spans, sel)
    tables = direct_init(spans, specs)
    cnt, accs = direct_update(tables, idx, total, contribs, specs,
                              kernel_mode=kernel_mode, merge=merge,
                              reuse_count=reuse_count)
    key_arrays, key_valids = direct_keys(domains, spans, strides,
                                         [v.dtype for v in key_vecs])
    return key_arrays, key_valids, accs, cnt > 0


def sort_aggregate(key_vecs: Sequence[Vec],
                   contribs: List[List], specs: List[List[AccSpec]],
                   sel, capacity: int, num_segments: Optional[int] = None
                   ) -> Tuple[List, List, List, object, object]:
    """General sort-based aggregation.

    Returns (key_arrays, key_validities, acc_arrays, occupied,
    total_groups). Groups beyond `num_segments` are dropped — the caller
    must flag `total_groups > num_segments` and retry with capacity
    (the join/exchange AQE loop pattern).
    """
    num_segments = num_segments or capacity
    operands = []
    invalid = jnp.zeros((capacity,), jnp.int32) if sel is None else \
        (~sel).astype(jnp.int32)
    operands.append(invalid)
    for vec in key_vecs:
        data = vec.data
        if vec.validity is not None:
            operands.append((~vec.validity).astype(jnp.int8))
            # neutralize data under NULL: two NULL keys must land in ONE
            # group even when their dead payloads differ (e.g. after a
            # union's dictionary remap)
            data = jnp.where(vec.validity, data,
                             jnp.zeros((), data.dtype))
        operands.append(data)
    num_keys = len(operands)
    operands.append(jnp.arange(capacity, dtype=jnp.int32))  # permutation payload
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    inv_sorted = sorted_ops[0].astype(jnp.bool_)
    valid_sorted = ~inv_sorted

    # group starts: first valid row, or any key component differing from prev
    diff = jnp.zeros((capacity,), jnp.bool_)
    for op in sorted_ops[1:num_keys]:
        shifted = jnp.roll(op, 1)
        diff = diff | (op != shifted)
    first = jnp.arange(capacity) == 0
    starts = (first | diff) & valid_sorted
    total_groups = jnp.sum(starts.astype(jnp.int32))
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
    gid = jnp.where(valid_sorted & (gid < num_segments), gid,
                    num_segments)  # OOB -> dropped (flagged by caller)

    # per-segment first/last sorted-row positions via NON-colliding
    # scatters (each segment writes each exactly once); every reduce
    # below reads prefix scans at these bounds — colliding scatter-adds
    # serialize on TPU (~300ms for 4M rows into 65k segments)
    pos = jnp.arange(capacity, dtype=jnp.int32)
    in_seg = gid < num_segments
    sidx = jnp.where(starts & in_seg, gid, num_segments)
    nxt_gid = jnp.concatenate(
        [gid[1:], jnp.full((1,), num_segments, gid.dtype)])
    ends = in_seg & (nxt_gid != gid)
    eidx = jnp.where(ends, gid, num_segments)
    start_pos = jnp.zeros((num_segments,), jnp.int32).at[sidx].set(
        pos, mode="drop")
    end_pos = jnp.zeros((num_segments,), jnp.int32).at[eidx].set(
        pos, mode="drop")
    present = jnp.zeros((num_segments,), jnp.bool_).at[sidx].set(
        jnp.ones((capacity,), jnp.bool_), mode="drop")
    occupied_cnt = jnp.where(present, end_pos - start_pos + 1, 0)

    accs = []
    for row_contribs, row_specs in zip(contribs, specs):
        fn_accs = []
        for contrib, spec in zip(row_contribs, row_specs):
            contrib_sorted = jnp.take(contrib, perm)
            out = _sorted_segment_reduce(contrib_sorted, spec.reduce,
                                         starts, start_pos, end_pos,
                                         present)
            if spec.reduce != "sum":
                neutral = jnp.full((num_segments,), spec.neutral)
                out = jnp.where(occupied_cnt > 0, out, neutral)
            fn_accs.append(out.astype(spec.np_dtype))
        accs.append(fn_accs)

    # scatter first-of-group key values into the output slots
    key_arrays = []
    key_valids = []
    oi = 1
    for vec in key_vecs:
        if vec.validity is not None:
            null_sorted = sorted_ops[oi].astype(jnp.bool_)
            oi += 1
        else:
            null_sorted = None
        data_sorted = sorted_ops[oi]
        oi += 1
        out = jnp.zeros((num_segments,), data_sorted.dtype).at[
            jnp.where(starts, gid, num_segments)].set(data_sorted, mode="drop")
        key_arrays.append(out)
        if null_sorted is not None:
            kv = jnp.ones((num_segments,), jnp.bool_).at[
                jnp.where(starts, gid, num_segments)].set(
                    ~null_sorted, mode="drop")
            key_valids.append(kv)
        else:
            key_valids.append(None)
    return key_arrays, key_valids, accs, occupied_cnt > 0, total_groups


# ---------------------------------------------------------------------------
# Positional aggregates: percentile/median/collect_list/collect_set
# (reference: ApproximatePercentile.scala:1 / Percentile.scala /
# collect.scala — ObjectHashAggregate's serialized per-group state
# becomes ONE device sort by (group keys, value) + segmented positional
# gathers; list outputs compact into offsets-encoded array columns)
# ---------------------------------------------------------------------------

def positional_sort(key_vecs: Sequence[Vec], value_vec: Vec, sel,
                    capacity: int):
    """Sort rows by (liveness, group keys, value-null-last, value).
    Returns (values_sorted, value_valid_sorted, starts, gid, start_pos,
    total_groups, group_occupied). Group ORDER depends only on the keys,
    so several positional sorts (different value children) and a
    sort_aggregate over the same keys all align group-for-group."""
    operands = []
    invalid = jnp.zeros((capacity,), jnp.int32) if sel is None else \
        (~sel).astype(jnp.int32)
    operands.append(invalid)
    for vec in key_vecs:
        data = vec.data
        if vec.validity is not None:
            operands.append((~vec.validity).astype(jnp.int8))
            data = jnp.where(vec.validity, data,
                             jnp.zeros((), data.dtype))
        operands.append(data)
    vinvalid = jnp.zeros((capacity,), jnp.int8) \
        if value_vec.validity is None else \
        (~value_vec.validity).astype(jnp.int8)
    operands.append(vinvalid)  # null values sort to the group tail
    operands.append(value_vec.data)
    num_keys = len(operands)
    operands.append(jnp.arange(capacity, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    valid_sorted = sorted_ops[0] == 0
    values_sorted = sorted_ops[-2]
    vvalid_sorted = (sorted_ops[-3] == 0) & valid_sorted

    diff = jnp.zeros((capacity,), jnp.bool_)
    i = 1
    for vec in key_vecs:
        if vec.validity is not None:
            op = sorted_ops[i]
            diff = diff | (op != jnp.roll(op, 1))
            i += 1
        op = sorted_ops[i]
        diff = diff | (op != jnp.roll(op, 1))
        i += 1
    first = jnp.arange(capacity) == 0
    starts = (first | diff) & valid_sorted
    total_groups = jnp.sum(starts.astype(jnp.int32))
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
    gid = jnp.where(valid_sorted, gid, capacity)

    pos = jnp.arange(capacity, dtype=jnp.int32)
    sidx = jnp.where(starts, jnp.clip(gid, 0, capacity), capacity)
    # GROUP-indexed first-row position (slot g -> group g's start)
    gstart = jnp.zeros((capacity,), jnp.int32).at[sidx].set(
        pos, mode="drop")
    # per-ROW segment-start position (running max of start markers)
    row_start = jax.lax.cummax(jnp.where(starts, pos, jnp.int32(0)))
    return (values_sorted, vvalid_sorted, starts, gid, gstart,
            row_start, total_groups, sorted_ops)


def positional_percentile(values_sorted, vvalid_sorted, gid, gstart,
                          num_segments: int, q: float, capacity: int):
    """Exact per-group percentile with linear interpolation (the
    reference's Percentile): values of each group sit contiguously with
    nulls at the tail, so the q-quantile is two gathers + a lerp.
    `gstart` is GROUP-indexed (slot g -> group g's first sorted row)."""
    cnt = jnp.zeros((num_segments + 1,), jnp.int32).at[
        jnp.clip(gid, 0, num_segments)].add(
        vvalid_sorted.astype(jnp.int32), mode="drop")[:num_segments]
    gstart = gstart[:num_segments]
    vals = values_sorted.astype(jnp.float64)
    idx = (cnt - 1).astype(jnp.float64) * q
    lo = jnp.clip(jnp.floor(idx).astype(jnp.int32), 0, None)
    hi = jnp.clip(jnp.ceil(idx).astype(jnp.int32), 0, None)
    safe = jnp.clip(gstart, 0, capacity - 1)
    v_lo = jnp.take(vals, jnp.clip(safe + lo, 0, capacity - 1))
    v_hi = jnp.take(vals, jnp.clip(safe + hi, 0, capacity - 1))
    frac = idx - lo.astype(jnp.float64)
    out = v_lo + (v_hi - v_lo) * frac
    return out, cnt > 0


def positional_collect(values_sorted, vvalid_sorted, gid, row_start,
                       num_segments: int, distinct: bool, capacity: int):
    """collect_list / collect_set: compact each group's (optionally
    deduplicated) valid values into an offsets-encoded list column.
    `row_start` is the PER-ROW segment-start position. Returns
    (data[cap], offsets[num_segments+1])."""
    keep = vvalid_sorted
    if distinct:
        same_prev = (jnp.roll(values_sorted, 1) == values_sorted) & \
            (jnp.roll(gid, 1) == gid) & \
            (jnp.arange(capacity) != 0)
        keep = keep & ~(same_prev & vvalid_sorted &
                        jnp.roll(vvalid_sorted, 1))
    kcnt = jnp.zeros((num_segments + 1,), jnp.int32).at[
        jnp.clip(gid, 0, num_segments)].add(
        keep.astype(jnp.int32), mode="drop")[:num_segments]
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(kcnt)]).astype(jnp.int32)
    ck = jnp.cumsum(keep.astype(jnp.int32))
    # rank of each kept row within its group's kept values
    ck_at_start = jnp.take(ck, row_start) - jnp.take(
        keep.astype(jnp.int32), row_start)
    rank = ck - ck_at_start - 1
    target = jnp.where(
        keep,
        jnp.take(new_off, jnp.clip(gid, 0, num_segments)) + rank,
        capacity)
    data = jnp.zeros((capacity,), values_sorted.dtype).at[
        target].set(values_sorted, mode="drop")
    return data, new_off

"""Differential optimizer fuzzer: seed-deterministic query generation
+ optimizer-on / optimizer-off / per-rule-ablated execution parity.

The plan-integrity verifier (`analysis/plan_integrity.py`) asserts
structural invariants; this harness turns it into a bug-finder. Each
seed deterministically generates a small table set — nulls everywhere,
NaN / -0.0 / +-inf floats, decimals, dictionary-encodable strings,
dates — and a random query tree (project / filter / join / aggregate /
sort / limit / union / distinct, with a SQL-text round-trip for a
slice of seeds), then runs it:

- optimizer OFF (`spark_tpu.sql.optimizer.excludedRules=*`) — the
  semantics baseline;
- optimizer ON under `planChangeValidation=full` (any invariant
  violation raises, naming the rule);
- per-rule ABLATED: every rule that was effective in the ON run is
  excluded one at a time — a wrong rewrite shows up as a parity break
  attributable to the excluded rule's absence;
- planned twice: optimized tree strings and physical `describe()`
  fingerprints (the stage-key roots, hence the persistent compile
  cache keys) must be identical across repeated planning.

Results compare via a canonical byte serialization: rows sorted by a
total order built from value BIT PATTERNS (so -0.0 vs 0.0 and real
value drift are caught; NaN payloads are canonicalized because two
IEEE-equal pipelines may emit different payload bits). Schema names
and arrow types compare; arrow-level nullability does not (rules may
legitimately tighten logical nullability).

`scripts/plan_fuzz.py` is the CLI; `tests/test_plan_integrity.py`
replays pinned seeds as regressions.
"""

from __future__ import annotations

import datetime
import decimal
import random
import struct
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

SEEDS_KEY = "spark_tpu.sql.fuzz.seeds"
MAX_ROWS_KEY = "spark_tpu.sql.fuzz.maxRows"
EXCLUDED_KEY = "spark_tpu.sql.optimizer.excludedRules"
VALIDATION_KEY = "spark_tpu.sql.planChangeValidation"

#: column-name pool shared across generated tables ON PURPOSE: name
#: collisions exercise the join `_r` rename chains
_COL_POOL = ("a", "b", "c", "d", "e", "f")
_STR_VOCAB = ("", "x", "y", "zz", "AA", "x", "mixed", "Mixed", "q")
_FLOAT_SPECIALS = (float("nan"), -0.0, 0.0, float("inf"), float("-inf"),
                   1.5, -2.25, 1e300, -1e-300)


class FuzzMismatch(AssertionError):
    """One seed's differential failure: which comparison broke and how."""

    def __init__(self, seed: int, stage: str, message: str):
        self.seed = seed
        self.stage = stage
        super().__init__(f"seed {seed} [{stage}]: {message}")


# ---------------------------------------------------------------------------
# Deterministic data generation
# ---------------------------------------------------------------------------


def _gen_column(rng: random.Random, dtype: str, n: int):
    """(values, arrow type) with ~15% nulls and adversarial values."""
    null_p = rng.choice((0.0, 0.15, 0.3))
    vals: list = []
    for _ in range(n):
        if rng.random() < null_p:
            vals.append(None)
        elif dtype == "int32":
            vals.append(rng.randint(-50, 50))
        elif dtype == "int64":
            vals.append(rng.choice((rng.randint(-1000, 1000),
                                    rng.randint(-3, 3))))
        elif dtype == "float64":
            vals.append(rng.choice(_FLOAT_SPECIALS)
                        if rng.random() < 0.4 else
                        rng.uniform(-100, 100))
        elif dtype == "decimal":
            vals.append(decimal.Decimal(rng.randint(-10**6, 10**6))
                        .scaleb(-2))
        elif dtype == "string":
            vals.append(rng.choice(_STR_VOCAB))
        else:  # date
            vals.append(datetime.date(1970, 1, 1)
                        + datetime.timedelta(days=rng.randint(-400, 400)))
    at = {"int32": pa.int32(), "int64": pa.int64(),
          "float64": pa.float64(), "decimal": pa.decimal128(12, 2),
          "string": pa.string(), "date": pa.date32()}[dtype]
    return vals, at


def gen_tables(rng: random.Random, max_rows: int
               ) -> Dict[str, pa.Table]:
    """1-3 tables over a shared column-name pool. Every table carries an
    int32 join key `k` over a small domain so generated joins always
    have a type-compatible, collision-rich key."""
    tables: Dict[str, pa.Table] = {}
    for ti in range(rng.randint(1, 3)):
        n_rows = rng.randint(3, max(3, max_rows))
        cols = rng.sample(_COL_POOL, rng.randint(2, 4))
        arrays, fields = [], []
        kvals = [None if rng.random() < 0.1 else rng.randint(0, 7)
                 for _ in range(n_rows)]
        arrays.append(pa.array(kvals, pa.int32()))
        fields.append(pa.field("k", pa.int32()))
        for cn in cols:
            dtype = rng.choice(("int32", "int64", "float64", "decimal",
                                "string", "date"))
            vals, at = _gen_column(rng, dtype, n_rows)
            arrays.append(pa.array(vals, at))
            fields.append(pa.field(cn, at))
        tables[f"fz{ti}"] = pa.Table.from_arrays(
            arrays, schema=pa.schema(fields))
    return tables


# ---------------------------------------------------------------------------
# Deterministic query generation
# ---------------------------------------------------------------------------


def _numeric_cols(df) -> List[str]:
    from .. import types as T
    return [f.name for f in df.schema.fields
            if isinstance(f.dtype, T.NumericType)]


def _int_cols(df) -> List[str]:
    from .. import types as T
    return [f.name for f in df.schema.fields
            if isinstance(f.dtype, T.IntegralType)]


def _gen_predicate(rng: random.Random, df):
    from .. import functions as F
    from .. import types as T
    from ..expr import And, Or
    fields = list(df.schema.fields)
    rng.shuffle(fields)

    def one(f):
        c = F.col(f.name)
        if isinstance(f.dtype, T.StringType):
            return c == F.lit(rng.choice(_STR_VOCAB))
        if isinstance(f.dtype, T.DateType):
            pivot = datetime.date(1970, 1, 1) + datetime.timedelta(
                days=rng.randint(-400, 400))
            return rng.choice((c < F.lit(pivot), c >= F.lit(pivot)))
        if isinstance(f.dtype, T.DecimalType):
            lit = F.lit(decimal.Decimal(rng.randint(-10**6, 10**6))
                        .scaleb(-2), f.dtype)
            return rng.choice((c <= lit, c > lit))
        lit = F.lit(rng.randint(-40, 40))
        op = rng.randrange(4)
        return (c > lit if op == 0 else c < lit if op == 1
                else c == lit if op == 2 else c != lit)

    pred = one(fields[0])
    if len(fields) > 1 and rng.random() < 0.4:
        combine = And if rng.random() < 0.7 else Or
        pred = combine(pred, one(fields[1]))
    return pred


def _gen_aggs(rng: random.Random, df, tag: int) -> list:
    """Aggregate list with `tag`-qualified aliases so stacked
    aggregations can't collide with group columns produced by an
    earlier aggregation step."""
    from .. import functions as F
    aggs = [F.count("*").alias(f"cnt{tag}")]
    nums = _numeric_cols(df)
    rng.shuffle(nums)
    for i, cn in enumerate(nums[:2]):
        fn = rng.choice((F.sum, F.min, F.max, F.avg))
        aggs.append(fn(F.col(cn)).alias(f"ag{tag}_{i}"))
    return aggs


def gen_query(rng: random.Random, session, tables: Dict[str, pa.Table]):
    """One random DataFrame query over the registered tables; the op
    sequence, expressions and literals are all drawn from `rng`, so a
    seed fully determines the plan."""
    from .. import functions as F
    names = sorted(tables)
    df = session.table(rng.choice(names))
    n_ops = rng.randint(1, 5)
    joined = False
    for step in range(n_ops):
        op = rng.choice(("project", "filter", "filter", "join", "agg",
                         "sort", "limit", "union", "distinct"))
        cols = df.columns
        if op == "project":
            keep = rng.sample(cols, rng.randint(1, len(cols)))
            exprs = [F.col(c) for c in keep]
            nums = _numeric_cols(df)
            if nums and rng.random() < 0.6:
                cn = rng.choice(nums)
                e = F.col(cn) + F.lit(rng.randint(1, 5)) \
                    if rng.random() < 0.5 else \
                    F.col(cn) * F.lit(rng.randint(-3, 3))
                exprs.append(e.alias(f"p{step}"))
            df = df.select(*exprs)
        elif op == "filter":
            df = df.filter(_gen_predicate(rng, df))
        elif op == "join" and not joined and "k" in cols:
            other = session.table(rng.choice(names))
            if "k" not in other.columns:
                continue
            how = rng.choice(("inner", "inner", "left", "right", "full",
                              "left_semi", "left_anti"))
            if rng.random() < 0.5:
                df = df.join(other, on="k", how=how)
            else:
                df = df.join(other, left_on=F.col("k"),
                             right_on=F.col("k"), how=how)
            joined = True
        elif op == "agg":
            group_pool = [c for c in cols
                          if rng.random() < 0.8] or cols[:1]
            groups = [F.col(c) for c in
                      rng.sample(group_pool,
                                 rng.randint(1, min(2, len(group_pool))))]
            df = df.group_by(*groups).agg(*_gen_aggs(rng, df, step))
        elif op == "sort":
            from ..expr import SortOrder
            keys = rng.sample(cols, rng.randint(1, min(2, len(cols))))
            df = df.sort(*[SortOrder(F.col(c),
                                     ascending=rng.random() < 0.7)
                           for c in keys])
        elif op == "limit":
            df = df.limit(rng.randint(0, 30))
        elif op == "union":
            df = df.union(df.filter(_gen_predicate(rng, df))
                          if rng.random() < 0.5 else df)
        elif op == "distinct":
            df = df.distinct()
    return df


def gen_sql(rng: random.Random, tables: Dict[str, pa.Table]
            ) -> Optional[str]:
    """A SQL-text round-trip case over the same generated tables:
    single-table select/where/group/order/limit or a two-table
    key-equi-join — the frontend slice the parser supports."""
    names = sorted(tables)
    t0 = rng.choice(names)
    # exclude `k` — the SELECT templates already project k, and a
    # duplicate projection (`SELECT k, k`) is legal but defeats the
    # zero-findings assertion the fuzzer makes about its own queries
    int_cols = [f.name for f in tables[t0].schema
                if pa.types.is_integer(f.type) and f.name != "k"]
    if not int_cols:
        return None
    key = rng.choice(int_cols)
    if len(names) > 1 and rng.random() < 0.4:
        t1 = rng.choice([n for n in names if n != t0])
        if "k" not in [f.name for f in tables[t1].schema]:
            return None
        return (f"SELECT {t0}.k, COUNT(*) AS cnt FROM {t0} "
                f"JOIN {t1} ON {t0}.k = {t1}.k "
                f"GROUP BY {t0}.k ORDER BY {t0}.k")
    shape = rng.randrange(3)
    if shape == 0:
        return (f"SELECT k, {key} FROM {t0} "
                f"WHERE {key} > {rng.randint(-20, 20)} ORDER BY k, {key}")
    if shape == 1:
        return (f"SELECT k, COUNT(*) AS cnt, SUM({key}) AS s FROM {t0} "
                f"GROUP BY k ORDER BY k")
    return (f"SELECT {key} + 1 AS kp FROM {t0} ORDER BY kp "
            f"LIMIT {rng.randint(0, 20)}")


# ---------------------------------------------------------------------------
# Canonical result serialization
# ---------------------------------------------------------------------------


def _keyval(v) -> tuple:
    """Total-order sort/serialization key distinguishing bit patterns
    (-0.0 vs 0.0) while canonicalizing NaN payloads."""
    if v is None:
        return (0,)
    if isinstance(v, bool):
        return (1, int(v))
    if isinstance(v, int):
        return (2, v)
    if isinstance(v, float):
        if v != v:
            return (3, "nan")
        return (3, struct.pack("<d", v).hex())
    if isinstance(v, decimal.Decimal):
        return (4, str(v))
    if isinstance(v, str):
        return (5, v)
    if isinstance(v, datetime.datetime):
        return (6, v.isoformat())
    if isinstance(v, datetime.date):
        return (6, v.isoformat())
    return (9, repr(v))


def canonical_bytes(table: pa.Table) -> bytes:
    """Order-independent, bit-exact serialization of a result table:
    schema (names + arrow types), then rows sorted by total-order keys."""
    header = repr([(f.name, str(f.type)) for f in table.schema])
    cols = [table.column(i).to_pylist()
            for i in range(table.num_columns)]
    rows = sorted(tuple(_keyval(c[r]) for c in cols)
                  for r in range(table.num_rows))
    return (header + "|" + repr(rows)).encode()


# ---------------------------------------------------------------------------
# The differential harness
# ---------------------------------------------------------------------------


def _collect(df) -> Tuple[bytes, object, str]:
    """Collect one fresh QueryExecution; the physical describe() (the
    stage-key root) is captured BEFORE execution because runtime
    adaptation (e.g. the unique-build demotion) legitimately mutates
    physical nodes after the fact."""
    qe = df._qe()
    desc = qe.executed_plan.describe()
    table = qe.collect()
    return canonical_bytes(table), qe, desc


def run_seed(session, seed: int, ablate: str = "effective",
             max_rows: Optional[int] = None) -> Dict:
    """Run one seed's differential checks. Returns a summary dict;
    raises `FuzzMismatch` (parity/stability breaks) or
    `PlanIntegrityError` (verifier violations) on failure. Session conf
    is snapshotted and restored."""
    if ablate not in ("none", "one", "effective", "all"):
        raise ValueError(f"invalid ablate mode {ablate!r}")
    conf = session.conf
    saved = dict(conf._settings)
    rng = random.Random(seed)
    try:
        tables = gen_tables(rng, int(max_rows if max_rows is not None
                                     else conf.get(MAX_ROWS_KEY)))
        for name, tbl in tables.items():
            session.register_table(name, tbl)
        sql = None
        if rng.random() < 0.25:
            sql = gen_sql(rng, tables)
        df = session.sql(sql) if sql else \
            gen_query(rng, session, tables)

        conf.set(VALIDATION_KEY, "full")
        # baseline: optimizer off (verifier still watches the — empty —
        # rule stream; checks nothing, proving parity is vs raw plan)
        conf.set(EXCLUDED_KEY, "*")
        base_bytes, _, _ = _collect(df)

        # optimizer on, full validation
        conf.set(EXCLUDED_KEY, "")
        on_bytes, qe, on_desc = _collect(df)
        if on_bytes != base_bytes:
            raise FuzzMismatch(
                seed, "optimizer-parity",
                f"optimizer-on result differs from optimizer-off "
                f"baseline\nplan:\n{qe.optimized_plan.tree_string()}\n"
                f"sql: {sql!r}")
        trace = qe.rule_trace or []

        # repeated planning: optimized tree + physical describe (the
        # stage-key root) must be byte-identical run to run
        qe2 = df._qe()
        if qe2.optimized_plan.tree_string() != \
                qe.optimized_plan.tree_string():
            raise FuzzMismatch(seed, "plan-stability",
                               "optimized plan differs across planning "
                               "runs")
        if qe2.executed_plan.describe() != on_desc:
            raise FuzzMismatch(seed, "stage-key-stability",
                               "physical describe() (stage-key root) "
                               "differs across planning runs")

        effective = [r["rule"] for r in trace if r["effective"] > 0]
        if ablate == "none":
            targets: List[str] = []
        elif ablate == "one":
            targets = effective[:1]
        elif ablate == "effective":
            targets = effective
        else:
            targets = sorted({r["rule"] for r in trace})
        for rule_name in targets:
            conf.set(EXCLUDED_KEY, rule_name)
            abl_bytes, abl_qe, _ = _collect(df)
            if abl_bytes != base_bytes:
                raise FuzzMismatch(
                    seed, f"ablation:{rule_name}",
                    f"result with rule {rule_name!r} ablated differs "
                    f"from baseline\nplan:\n"
                    f"{abl_qe.optimized_plan.tree_string()}\n"
                    f"sql: {sql!r}")
        return {"seed": seed, "sql": bool(sql),
                "effective_rules": effective,
                "ablations": len(targets)}
    finally:
        conf._settings.clear()
        conf._settings.update(saved)


def run_campaign(session, seeds, ablate: str = "effective",
                 max_rows: Optional[int] = None,
                 stop_on_fail: bool = False,
                 progress=None) -> Dict:
    """Run many seeds; collect failures instead of dying on the first
    (unless `stop_on_fail`). Returns {"ok": [...], "failures":
    [(seed, repr(error))...], "effective_counts": {rule: n}}."""
    ok: List[int] = []
    failures: List[Tuple[int, str]] = []
    eff: Dict[str, int] = {}
    for n, seed in enumerate(seeds):
        if n and n % 25 == 0:
            # Every seed compiles unique stages, so the in-process
            # executable caches grow without bound over a long campaign
            # — LLVM eventually dies with "Cannot allocate memory".
            # Periodic eviction trades recompiles for bounded memory.
            import jax
            session._stage_cache.clear()
            jax.clear_caches()
        try:
            res = run_seed(session, seed, ablate=ablate,
                           max_rows=max_rows)
            ok.append(seed)
            for r in res["effective_rules"]:
                eff[r] = eff.get(r, 0) + 1
        except Exception as e:  # noqa: BLE001 — campaign collects
            failures.append((seed, f"{type(e).__name__}: {e}"))
            if stop_on_fail:
                break
        if progress is not None:
            progress(seed, not failures or failures[-1][0] != seed)
    return {"ok": ok, "failures": failures, "effective_counts": eff}

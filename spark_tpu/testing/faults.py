"""Conf-driven deterministic fault injection for chaos testing.

The reference exercises its failure machinery with test-only hooks
(`TaskSchedulerImplSuite`, `FetchFailedException` fixtures, the
`spark.test.*` knobs); an XLA engine has no task boundaries to kill, so
this module plants NAMED INJECTION POINTS at the host-side seams of
stage execution — scan ingest, stage compile, stage dispatch, shuffle
lowering, join builds, the mesh path — and arms them from one conf
string:

    spark_tpu.faults.inject = "shuffle:resource_exhausted:2,join_build:unavailable:1"

Grammar (comma-separated rules):

    rule  := site ":" fault ":" nth [":" arg]
    site  := scan_load | stage_compile | stage_run | shuffle
             | join_build | mesh | stream_chunk | mesh_checkpoint
             | ingest_prefetch | shard_chunk | mesh_restart
             | decommission | stream_source_list
             | stream_offset_write | stream_state_commit
             | stream_sink_emit | compile_cache_load | cancel_point
             | udf_batch | udf_worker_spawn | stream_net_connect
             | stream_net_recv | trigger_tick | state_spill
             | fleet_worker
             (KNOWN_SITES: the wired seams)
    fault := resource_exhausted | unavailable | deadline | fatal | slow
             | cancel
    nth   := 1-based hit count of `site` at which the rule fires
    arg   := fault argument (only `slow`: sleep milliseconds, default 100)

Each rule fires exactly ONCE (later hits of the same site pass), so a
retry loop that re-executes the site deterministically succeeds — the
chaos suite proves recovery, not permanent outage. Multiple rules on one
site with different `nth` model repeated failures.

Raising faults carry messages shaped like the real XLA/PJRT errors
("RESOURCE_EXHAUSTED: ...", "UNAVAILABLE: ..."), so the executor's
failure taxonomy (execution/failures.py) classifies synthetic and real
errors through the same path. `slow` sleeps instead of raising — the
deterministic trigger for the stage wall-clock deadline
(spark_tpu.execution.stageTimeoutMs).

Sites fire at Python execution time: host-side sites (scan_load,
stage_run) fire on every pass; in-trace sites (shuffle, join_build) fire
at TRACE time, i.e. once per (re)compile of the enclosing stage — the
executor drops the failed stage's compiled entry on retry, so the retry
re-traces and the site counts deterministically. `stream_chunk` fires
once per chunk ATTEMPT inside the streaming drivers' chunk loops
(execution/recovery.py, so replays re-fire and later hits can target
retries); `ingest_prefetch` fires once per chunk host-decode attempt on
the prefetcher's background thread (io/sources.py, same per-chunk retry
path); `mesh_checkpoint` fires at each mesh-stream snapshot point,
before the snapshot is taken; `shard_chunk` fires once per
(chunk, shard) inside the per-shard telemetry's timed wait window
(observability/spans.py — hit ordinal chunk * n_shards + shard + 1),
so a `slow` rule models exactly one straggling shard for the
StragglerMonitor chaos tests; `mesh_restart` fires at each
gang-restart attempt boundary (parallel/elastic.py — a raising rule
fails THAT attempt, consuming its budget, so `mesh_restart:fatal`
proves the ladder still lands on single-device fallback);
`decommission` fires at the drain boundary, before the forced
checkpoint (a raising rule models the drain machinery dying and rides
the normal mesh ladder).

The four `stream_*` micro-batch seams (streaming.py +
execution/state_store.py) each fire BEFORE their boundary's action, so
an armed `fatal` rule models a hard crash AT that point with nothing
of the action persisted: `stream_source_list` before the loop polls
the source for new offsets, `stream_offset_write` before the planned
range lands in the offset log, `stream_state_commit` at every state
-store commit entry (delta or snapshot, nothing written yet), and
`stream_sink_emit` before the batch's output reaches the sink. The
durability chaos matrix (tests/test_streaming_durability.py) kills a
query at each seam, discards the object, and proves a fresh
StreamingQuery over the same checkpoint recovers exactly-once.

`compile_cache_load` fires inside the persistent compile cache's
guarded entry load (execution/compile_cache.py), once per existing
entry consulted: an armed rule models a corrupted/truncated entry (or
a backend deserialize rejection), and the contract under ANY failure
there is log + count (`compile_cache_corrupt`) + fresh compile +
overwrite — a damaged cache never fails a query.

`cancel_point` fires at EVERY cooperative cancellation boundary
(execution/lifecycle.py `checkpoint`): stage-attempt entry, compile
entry, scan ingest, every chunk of every chunk driver, retry-backoff
entry, admission-queue and arbiter-lease wait wakeups, and the
streaming trigger loop. Paired with the `cancel` fault class — which
CANCELS the context's installed token instead of raising, so the very
checkpoint that fired the rule then raises the structured
QueryCancelledError — a `cancel_point:cancel:n` rule delivers a
cancellation at exactly the nth boundary a query crosses: the
cancel-point chaos matrix (tests/test_lifecycle.py) sweeps `n` across
execution shapes to prove every boundary releases its resources.

The unattended-streaming seams extend the micro-batch set to the
network tier (io/network_source.py + streaming.py +
execution/external.py): `stream_net_connect` fires before every socket
connect ATTEMPT (first connect and every reconnect-ladder rung, so
`nth` targets a specific rung), `stream_net_recv` before each frame
read off the wire (nothing of that frame persisted yet — a `fatal`
there models the consumer dying mid-stream, and the offset handshake
on the next connect proves zero loss/zero duplication),
`trigger_tick` at the top of every supervised trigger-loop tick
(before the tick's `process_available`, so a crash there loses the
whole tick and the restart supervisor classifies it), and
`state_spill` before each spill-partition write in the host-spillable
keyed state backend (the partition file is the action — nothing
written yet when the rule fires). The unattended chaos matrix
(tests/test_streaming_unattended.py) kills at each seam and proves a
fresh query over the same checkpoint recovers byte-identically.

`udf_batch` fires once per batch ATTEMPT inside the out-of-process UDF
lane's per-slice retry loop (execution/python_eval.py worker mode —
the seam sits inside the ChunkRetrier step, so replays re-fire). A
`fatal` rule there is special-cased by the lane into a real
SIGKILL-mid-batch model: the in-flight worker is killed and the error
surfaces as UdfWorkerLost (UNAVAILABLE -> TRANSIENT), proving exactly
one batch replays on a fresh worker. `udf_worker_spawn` fires before
each worker subprocess exec (udf_worker/pool.py), so spawn failures
ride the same batch-replay path.

`fleet_worker` fires before each worker-subprocess spawn attempt in
the fleet supervisor (service/fleet.py — the `udf_worker_spawn`
pattern one tier up): a raising rule models a worker that dies at
boot, which rides the supervisor's RetryPolicy restart ladder and, at
`restartMaxPerWindow` crashes within the window, trips the flap
breaker into quarantine — the chaos vehicle for the fleet's
graceful-degradation tests (tests/test_fleet.py).

The `slow` fault sleeps on the INTERRUPTIBLE lifecycle wait, not a
bare time.sleep: a cancel/deadline delivered mid-sleep wakes it
immediately (raising the structured lifecycle error), so cancel-matrix
cells that combine slow faults with cancellation terminate promptly.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

INJECT_KEY = "spark_tpu.faults.inject"

#: the wired-seam registry: every site here has a `faults.fire(site)`
#: call planted in the engine (the `fault-site` lint pass proves both
#: directions statically). `_parse` validates rule sites against this
#: set at ARM time — a typo'd site (`stage_rnu`) used to parse fine and
#: then silently never fire, so the chaos test tested nothing.
KNOWN_SITES = ("scan_load", "stage_compile", "stage_run", "shuffle",
               "join_build", "mesh", "stream_chunk", "mesh_checkpoint",
               "ingest_prefetch", "shard_chunk", "mesh_restart",
               "decommission", "stream_source_list",
               "stream_offset_write", "stream_state_commit",
               "stream_sink_emit", "compile_cache_load",
               "cancel_point", "udf_batch", "udf_worker_spawn",
               "stream_net_connect", "stream_net_recv",
               "trigger_tick", "state_spill", "fleet_worker")

#: sites that fire INSIDE a stage trace (once per (re)compile of the
#: enclosing stage). The persistent compile cache consults this: a
#: deserialized executable involves no trace, so while a plan with
#: rules on these sites is armed, `_compile_stage` bypasses the disk
#: cache entirely — chaos determinism (retry re-traces, the rule's
#: nth hit arrives) wins over caching, and no plan is ever armed in
#: production. (`mesh` fires host-side in _compile_stage itself, and
#: scan_load/stage_run per pass — only these two are trace-bound.)
TRACE_TIME_SITES = ("shuffle", "join_build")

#: test-registered extra seams (register_site): code under test may
#: plant its own fire() points without editing the built-in tuple.
#: Unguarded by design (guarded-by waiver): registration happens at
#: test setup, before the seams it names run concurrently.
_EXTRA_SITES: set = set()


def register_site(site: str) -> str:
    """Declare an ad-hoc injection seam (tests planting their own
    `faults.fire(site)` points). Returns the site for inline use.
    Registration is process-global — prefer `scoped_site` in tests so
    a leaked registration can't quietly re-open the silent-no-fire
    hole the parse-time site validation closes."""
    _EXTRA_SITES.add(site)
    return site


def unregister_site(site: str) -> None:
    _EXTRA_SITES.discard(site)


@contextlib.contextmanager
def scoped_site(site: str):
    """`register_site` bounded to a with-block (the test idiom)."""
    register_site(site)
    try:
        yield site
    finally:
        unregister_site(site)


def known_sites() -> tuple:
    return KNOWN_SITES + tuple(sorted(_EXTRA_SITES))

#: raising fault classes -> message templates shaped like real errors
_MESSAGES = {
    "resource_exhausted":
        "RESOURCE_EXHAUSTED: injected: out of memory while allocating "
        "device buffer at {site} (hit {n})",
    "unavailable":
        "UNAVAILABLE: injected: backend endpoint unreachable at "
        "{site} (hit {n})",
    "deadline":
        "DEADLINE_EXCEEDED: injected: operation deadline exceeded at "
        "{site} (hit {n})",
    "fatal":
        "INTERNAL: injected: unrecoverable failure at {site} (hit {n})",
}

FAULT_CLASSES = tuple(_MESSAGES) + ("slow", "cancel")


class FaultInjected(Exception):
    """Synthetic error raised by an armed injection point. Carries the
    site and fault class so the taxonomy can classify without string
    matching (real errors still classify by message tokens)."""

    def __init__(self, site: str, fault: str, message: str):
        super().__init__(message)
        self.site = site
        self.fault = fault


@dataclass
class _Rule:
    site: str
    fault: str
    nth: int
    arg: Optional[float] = None
    fired: bool = False


def _parse(spec: str) -> List[_Rule]:
    rules = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) not in (3, 4):
            raise ValueError(
                f"bad fault rule {part!r}: want site:fault:nth[:arg]")
        site, fault = bits[0].strip(), bits[1].strip()
        if site not in known_sites():
            raise ValueError(
                f"unknown fault site {site!r} in rule {part!r}: no "
                f"faults.fire({site!r}) seam is wired, so the rule "
                f"could never fire; known sites: {known_sites()}")
        if fault not in FAULT_CLASSES:
            raise ValueError(
                f"unknown fault class {fault!r} in {part!r}; "
                f"known: {FAULT_CLASSES}")
        nth = int(bits[2])
        if nth < 1:
            raise ValueError(f"hit count must be >= 1 in {part!r}")
        arg = float(bits[3]) if len(bits) == 4 else None
        rules.append(_Rule(site, fault, nth, arg))
    return rules


class FaultPlan:
    """Parsed spec + per-site hit counters + a log of fired rules.

    Hit counting is lock-guarded: `ingest_prefetch` fires from the
    prefetcher's worker thread and the SQL service runs queries on
    pool threads, so concurrent fire() calls must not lose counts.
    Within one thread a site's nth targeting stays deterministic
    (decode/attempt order); across threads only the COUNT is
    guaranteed — a rule that must land on a specific chunk of a
    specific stream should be the only rule armed for its site."""

    def __init__(self, spec: str):
        self.spec = spec
        self.rules = _parse(spec)
        self.hits = {}
        self.fired_log: List[Tuple[str, int, str]] = []
        import threading
        self._lock = threading.Lock()

    def fire(self, site: str) -> None:
        with self._lock:
            n = self.hits.get(site, 0) + 1
            self.hits[site] = n
            due = []
            for r in self.rules:
                if r.fired or r.site != site or r.nth != n:
                    continue
                r.fired = True
                self.fired_log.append((site, n, r.fault))
                due.append(r)
        # fault effects run OUTSIDE the lock: a `slow` sleep must not
        # serialize unrelated sites' counting
        for r in due:
            if r.fault == "slow":
                # interruptible: a cancel/deadline delivered mid-sleep
                # wakes immediately and raises the structured
                # lifecycle error instead of blocking cancellation
                # for the full injected latency
                from ..execution import lifecycle
                lifecycle.sleep(
                    (r.arg if r.arg is not None else 100.0) / 1e3)
                continue
            if r.fault == "cancel":
                # cancel the context's installed token: the boundary
                # that fired this rule (lifecycle.checkpoint) raises
                # the structured QueryCancelledError right after
                from ..execution import lifecycle
                lifecycle.cancel_current()
                continue
            raise FaultInjected(
                site, r.fault, _MESSAGES[r.fault].format(site=site, n=n))


#: the single armed plan, shared by every thread that reaches a seam
#: (driver, prefetch workers, service pool threads); its hit counters
#: are lock-guarded — see FaultPlan. Rebinds (arm/reset) are atomic
#: reference stores at execution entry — waived in the guarded-by
#: registry; per-thread suppression is the ContextVar below, never a
#: plan swap.
_PLAN: Optional[FaultPlan] = None

#: thread-confined suppression flag (see `suppressed`): ContextVars
#: start fresh per thread, so a prefetch worker spawned during an
#: analysis re-trace still fires its seams
_SUPPRESS: ContextVar[bool] = ContextVar(
    "spark_tpu_faults_suppress", default=False)


def arm(conf) -> None:
    """Arm/disarm from conf. Called at every execute_batch entry: an
    unchanged spec KEEPS its hit counters (multi-execution scenarios
    count across queries); a changed spec starts fresh."""
    global _PLAN
    spec = str(conf.get(INJECT_KEY) or "").strip()
    if not spec:
        _PLAN = None
        return
    if _PLAN is None or _PLAN.spec != spec:
        _PLAN = FaultPlan(spec)


def reset() -> None:
    """Drop the armed plan and its hit counters."""
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def fire(site: str) -> None:
    """The injection point: no-op unless a plan is armed and this
    thread is not inside `suppressed()`. Cheap enough to sit on hot
    paths (one None check when disarmed)."""
    if _PLAN is not None and not _SUPPRESS.get():
        _PLAN.fire(site)


@contextlib.contextmanager
def suppressed():
    """Temporarily disarm injection for THIS THREAD without losing the
    plan's counters. The observability layer's cost-analysis lowering
    re-traces a stage; trace-time sites (shuffle, join_build, mesh)
    must count once per REAL compile, so analysis-only traces run
    under this guard. Suppression is a ContextVar, not a plan swap:
    the old `_PLAN = None` rebind disarmed the plan PROCESS-WIDE, so a
    concurrent query's real compile on another service thread (or a
    prefetch worker's decode) silently skipped its seams while any
    thread was inside an analysis re-trace."""
    token = _SUPPRESS.set(True)
    try:
        yield
    finally:
        _SUPPRESS.reset(token)


@contextlib.contextmanager
def inject(conf, spec: str):
    """Scoped injection for tests: set the conf spec with FRESH hit
    counters, restore and disarm on exit. Yields the armed FaultPlan so
    assertions can inspect `fired_log`."""
    old = conf.get(INJECT_KEY)
    conf.set(INJECT_KEY, spec)
    reset()
    arm(conf)
    try:
        yield active()
    finally:
        conf.set(INJECT_KEY, old if old else "")
        reset()

"""Runtime lock verification: observed order, hold times, contention.

The dynamic half of the concurrency analyzer
(`spark_tpu/analysis/concurrency/`): the static passes prove the
DECLARED lock graph acyclic and rank-ascending; lockwatch wraps the
live lock objects at test time and records what threads ACTUALLY do —

- acquisition-order edges (lock held -> lock acquired, per thread),
  asserted consistent with the same registry ranks the static graph
  was proven against (`assert_order_consistent`);
- hold time per lock (total + max) and contention (acquisitions that
  found the lock taken), for spotting critical sections that grew;
- daemon-thread hygiene: `assert_no_thread_leak` proves no worker
  (e.g. the ingest prefetcher) outlives its query.

Opt-in and test-only: `LockWatch().install_service(svc)` swaps the
known lock attributes for recording proxies; `uninstall()` restores
them. Per-instance leaf locks (each metrics Counter/Timer) are not
wrapped — they rank above everything and acquire nothing.

    watch = LockWatch()
    watch.install_service(svc)       # + watch.install_session(s)
    try:
        ... run concurrent queries ...
        watch.assert_order_consistent()
        watch.assert_no_thread_leak()
    finally:
        watch.uninstall()
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

#: the most recently ENTERED LockWatch (cleared by uninstall): the
#: flight recorder's bundle dump reads it via `current_watch()` so
#: crash bundles under test carry the observed lock report. Written
#: only from test setup/teardown — no lock needed (GIL-atomic ref).
_CURRENT: Optional["LockWatch"] = None


def current_watch() -> Optional["LockWatch"]:
    """The active LockWatch, if a test installed one (None in
    production — lockwatch is opt-in and test-only)."""
    return _CURRENT


class _WatchedLock:
    """Recording proxy over a Lock/RLock: context-manager + explicit
    acquire/release, delegating to the wrapped lock."""

    def __init__(self, watch: "LockWatch", lock_id: str, inner):
        self._watch = watch
        self._lock_id = lock_id
        self._inner = inner

    # -- lock protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        contended = not self._inner.acquire(blocking=False)
        if contended:
            if not blocking:
                self._watch._note_contended(self._lock_id)
                return False
            ok = self._inner.acquire(True, timeout)
            if not ok:
                self._watch._note_contended(self._lock_id)
                return False
        self._watch._note_acquired(self._lock_id, contended,
                                   time.perf_counter() - t0,
                                   obj=id(self._inner))
        return True

    def release(self):
        self._watch._note_released(self._lock_id)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class _WatchedCondition(_WatchedLock):
    """Condition proxy: `wait` releases the lock for its duration, so
    the held-stack entry is popped around the inner wait and re-pushed
    on wakeup (the re-acquisition records its edges again)."""

    def wait(self, timeout: Optional[float] = None):
        self._watch._note_released(self._lock_id)
        try:
            return self._inner.wait(timeout)
        finally:
            self._watch._note_acquired(self._lock_id, False, 0.0,
                                       obj=id(self._inner))

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._watch._note_released(self._lock_id)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._watch._note_acquired(self._lock_id, False, 0.0,
                                       obj=id(self._inner))

    def notify(self, n: int = 1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()


class LockWatch:
    """Process-wide recorder over wrapped locks. Internal state is
    guarded by its OWN plain lock (never itself watched)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        #: (held_id, acquired_id) -> count, across all threads
        self.edge_counts: Dict[Tuple[str, str], int] = {}
        #: lock_id -> {"acquires", "contended", "wait_s", "hold_s",
        #:             "max_hold_s"}
        self.lock_stats: Dict[str, Dict[str, float]] = {}
        self._installed: List[Tuple[object, str, object]] = []

    # -- recording (called from the proxies) --------------------------------

    def _held(self) -> List[List]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquired(self, lock_id: str, contended: bool,
                       wait_s: float, obj: int = 0) -> None:
        stack = self._held()
        with self._mu:
            st = self.lock_stats.setdefault(
                lock_id, {"acquires": 0, "contended": 0, "wait_s": 0.0,
                          "hold_s": 0.0, "max_hold_s": 0.0})
            st["acquires"] += 1
            st["wait_s"] += wait_s
            if contended:
                st["contended"] += 1
            # edges from every DISTINCT held lock object: a same-id
            # pair of different objects (two sessions' leases, two
            # sessions' buses) is exactly the ABBA deadlock shape a
            # rank check cannot see, so it records as a self-edge and
            # assert_order_consistent flags it; a reentrant re-acquire
            # of the SAME object (RLock, Condition.wait re-push) does
            # not
            for h_id, _, h_obj in stack:
                if h_id != lock_id or (obj and h_obj and h_obj != obj):
                    key = (h_id, lock_id)
                    self.edge_counts[key] = \
                        self.edge_counts.get(key, 0) + 1
        stack.append([lock_id, time.perf_counter(), obj])

    def _note_contended(self, lock_id: str) -> None:
        with self._mu:
            st = self.lock_stats.setdefault(
                lock_id, {"acquires": 0, "contended": 0, "wait_s": 0.0,
                          "hold_s": 0.0, "max_hold_s": 0.0})
            st["contended"] += 1

    def _note_released(self, lock_id: str) -> None:
        stack = self._held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == lock_id:
                _, t0, _ = stack.pop(i)
                hold = time.perf_counter() - t0
                with self._mu:
                    st = self.lock_stats.get(lock_id)
                    if st is not None:
                        st["hold_s"] += hold
                        st["max_hold_s"] = max(st["max_hold_s"], hold)
                return

    # -- installation -------------------------------------------------------

    def watch_attr(self, obj, attr: str, lock_id: str) -> None:
        """Swap `obj.<attr>` for a recording proxy (idempotent per
        (obj, attr))."""
        inner = getattr(obj, attr)
        if isinstance(inner, _WatchedLock):
            return
        cls = _WatchedCondition if hasattr(inner, "notify_all") \
            else _WatchedLock
        setattr(obj, attr, cls(self, lock_id, inner))
        self._installed.append((obj, attr, inner))
        global _CURRENT
        _CURRENT = self

    def install_service(self, svc) -> None:
        """Wrap a SqlService's locks + the process device cache + every
        pooled session present at call time (warm the pool first, or
        call again after new sessions appear)."""
        from ..execution import lifecycle
        from ..io.device_cache import CACHE
        self.watch_attr(svc.admission, "_cv", "service.admission")
        self.watch_attr(svc.session_quota, "_lock", "service.quota")
        self.watch_attr(lifecycle, "_TOKENS_LOCK", "execution.lifecycle")
        self.watch_attr(svc.arbiter, "_cv", "service.arbiter")
        self.watch_attr(svc.arbiter.result_cache, "_lock",
                        "service.result_cache")
        self.watch_attr(svc.pool, "_lock", "service.pool")
        self.watch_attr(svc, "_records_lock", "service.records")
        self.watch_attr(svc, "_async_lock", "service.async")
        self.watch_attr(svc, "_install_lock", "service.install")
        self.watch_attr(svc.history, "_lock", "service.history")
        self.watch_attr(svc.metrics, "_lock", "metrics.registry")
        self.watch_attr(svc.metrics, "_flush_lock", "metrics.flush")
        self.watch_attr(svc.bus, "_lock", "obs.bus")
        self.watch_attr(svc.status_store, "_lock", "obs.status")
        self.watch_attr(CACHE, "_lock", "io.device_cache")
        for entry in svc.pool._entries.values():
            self.watch_attr(entry, "lock", "service.session")
            self.install_session(entry.session)

    def install_session(self, session) -> None:
        """Wrap one session's bus + built-in listener locks (+ its
        metrics registry when not the service-shared one)."""
        from ..observability.flight_recorder import FlightRecorder
        from ..observability.sinks import EventLogListener
        from ..observability.straggler import StragglerMonitor
        self.watch_attr(session.listeners, "_lock", "obs.bus")
        self.watch_attr(session.metrics, "_lock", "metrics.registry")
        self.watch_attr(session.metrics, "_flush_lock", "metrics.flush")
        self.watch_attr(session._udf_pool, "_cv", "udf.pool")
        for li in session.listeners.listeners:
            if isinstance(li, EventLogListener):
                self.watch_attr(li, "_write_lock", "obs.event_log")
            elif isinstance(li, StragglerMonitor):
                self.watch_attr(li, "_lock", "obs.straggler")
            elif isinstance(li, FlightRecorder):
                self.watch_attr(li, "_lock", "obs.flightrec")

    def install_faults(self) -> None:
        """Wrap the currently-armed fault plan's counter lock (call
        after `faults.arm`/`faults.inject` created it)."""
        from . import faults
        plan = faults.active()
        if plan is not None:
            self.watch_attr(plan, "_lock", "faults.plan")

    def uninstall(self) -> None:
        """Restore every wrapped attribute (reverse order)."""
        global _CURRENT
        for obj, attr, inner in reversed(self._installed):
            setattr(obj, attr, inner)
        self._installed.clear()
        if _CURRENT is self:
            _CURRENT = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- verdicts -----------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self.edge_counts)

    def report(self) -> Dict:
        with self._mu:
            return {
                "edges": {f"{a} -> {b}": n
                          for (a, b), n in sorted(self.edge_counts.items())},
                "locks": {k: dict(v)
                          for k, v in sorted(self.lock_stats.items())},
            }

    def assert_order_consistent(self) -> None:
        """Every observed acquisition edge must ascend in the registry
        ranking (the order the static lock-order pass proved acyclic),
        and no edge may have been observed in both directions."""
        from ..analysis.concurrency.registry import rank_of
        edges = self.edges()
        problems = []
        for (a, b), n in sorted(edges.items()):
            if a == b:
                # recorded only for DISTINCT lock objects sharing one
                # id (see _note_acquired): two sessions' leases nested
                # is an ABBA deadlock shape no rank can order
                problems.append(
                    f"distinct {a!r} locks nested on one thread "
                    f"({n}x): same-rank ABBA deadlock shape")
                continue
            if (b, a) in edges:
                problems.append(
                    f"edge observed in BOTH directions: {a!r} <-> "
                    f"{b!r} (classic deadlock shape)")
            ra, rb = rank_of(a), rank_of(b)
            if ra is None or rb is None:
                problems.append(
                    f"edge touches unregistered lock: {a!r} -> {b!r}")
            elif ra >= rb:
                problems.append(
                    f"observed order inverts the registry ranking: "
                    f"{a!r} (rank {ra}) held while acquiring {b!r} "
                    f"(rank {rb}), {n}x")
        assert not problems, (
            "lockwatch: observed acquisition order inconsistent with "
            "the static lock-order registry:\n  "
            + "\n  ".join(problems)
            + f"\nfull report: {self.report()}")

    def assert_no_thread_leak(
            self, prefix: str = "spark-tpu-ingest-prefetch",
            timeout_s: float = 10.0) -> None:
        """No daemon thread with the given name prefix may outlive the
        queries that spawned it (bounded wait: a worker observed
        mid-exit gets `timeout_s` to finish)."""
        deadline = time.monotonic() + timeout_s
        while True:
            leaked = [t.name for t in threading.enumerate()
                      if t.name.startswith(prefix) and t.is_alive()]
            if not leaked:
                return
            if time.monotonic() >= deadline:
                raise AssertionError(
                    f"lockwatch: {len(leaked)} thread(s) with prefix "
                    f"{prefix!r} still alive {timeout_s}s after the "
                    f"queries ended: {leaked}")
            time.sleep(0.05)

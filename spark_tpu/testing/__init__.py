"""Test-support subsystems that ship with the engine (not the test
suite): deterministic fault injection for chaos testing lives in
`spark_tpu.testing.faults` — the ChaosMonkey/`FailureSafeParser` seat,
sized to a single-process SPMD engine."""
